"""Device anatomy (tigerbeetle_tpu/latency.py DeviceAnatomy + the
models/ledger.py compile sentinel + the stitch_trace XLA bridge).

Contracts under test:

- device sub-legs are CONSECUTIVE stamp intervals: a finished apply
  record's sub-legs sum to its apply e2e exactly (accounted_ratio 1.0
  at device granularity) — with a fake clock AND through a live
  follower DualLedger;
- a forced applier stall (`_test_apply_delay_s`) makes queue_wait the
  dominant sub-leg, and the flight-recorder/`--watch` line grows the
  device columns (dev_q, dev_dominant naming queue_wait);
- every device.* metric name is CATALOG'd with kind + unit + help
  (drift guard, same contract as latency.*/cdc.*/ingress.*);
- the compile sentinel counts cold compiles, stays silent on cache
  hits, and flags a compile after mark_warm() as a post-warmup event;
- the XLA trace bridge clock-aligns a jax.profiler dump onto the span
  clock via the device_trace_meta.json anchor and re-pids device
  events after the span-dump pids;
- device stamping is observability only: two same-seed follower runs
  with every op sampled produce identical device code-stream digests.
"""

import gzip
import json
from time import perf_counter_ns

import numpy as np

import tests.conftest  # noqa: F401 — CPU platform before jax init
from tigerbeetle_tpu import types
from tigerbeetle_tpu.latency import (
    DEVICE_LEGS,
    DLEG_BUSY,
    DLEG_COALESCE,
    DLEG_DISPATCH,
    DLEG_H2D,
    NULL_DEVICE_ANATOMY,
    DeviceAnatomy,
    device_leg_totals,
    dominant_leg,
)
from tigerbeetle_tpu.metrics import CATALOG, Metrics
from tigerbeetle_tpu.tracer import NULL_TRACER
from tigerbeetle_tpu.types import Operation


class _FakeClock:
    def __init__(self, deltas=(1000,)):
        self.t = 0
        self.deltas = list(deltas)
        self.i = 0

    def __call__(self):
        self.t += self.deltas[self.i % len(self.deltas)]
        self.i += 1
        return self.t


# -- pure DeviceAnatomy ------------------------------------------------


def test_device_sublegs_partition_apply_e2e_exactly():
    m = Metrics()
    a = DeviceAnatomy(metrics=m, clock=_FakeClock([700, 4000, 90, 12000]))
    tok = a.open(0xD1, t_enq=100)  # t_deq from clock: queue_wait = 700-100
    assert tok == 0xD1
    for leg in (DLEG_COALESCE, DLEG_H2D, DLEG_DISPATCH, DLEG_BUSY):
        a.stamp(tok, leg)
    a.finish(tok)
    rec = a.slowest()[0]
    assert rec["trace"] == f"{0xD1:016x}"
    assert abs(sum(rec["legs"].values()) - rec["e2e_us"]) < 1e-6, rec
    assert rec["dominant"] in rec["legs"]
    snap = m.snapshot()
    assert snap["counters"]["device.samples"] == 1
    assert snap["histograms"]["device.apply_e2e_us"]["count"] == 1
    # the folded per-sub-leg histogram totals partition e2e too
    totals = device_leg_totals(snap)
    total_us = sum(v["total_us"] for v in totals.values())
    e2e_us = snap["histograms"]["device.apply_e2e_us"]["mean"]
    assert abs(total_us - e2e_us) < 1e-3


def test_device_anatomy_explicit_stamps_and_dup_open():
    a = DeviceAnatomy(metrics=Metrics(), clock=_FakeClock())
    assert a.open(7, t_enq=1000, t_deq=3000) == 7
    assert a.open(7, t_enq=1000) == 0  # duplicate id
    assert a.open(0, t_enq=1000) == 0  # unsampled
    a.stamp(7, DLEG_DISPATCH, t=5000)
    a.finish(7, t=9000)
    rec = a.slowest()[0]
    assert rec["legs"]["queue_wait"] == 2.0  # (3000-1000) ns -> us
    assert rec["legs"]["dispatch"] == 2.0
    assert rec["legs"]["finalize_visible"] == 4.0
    assert rec["e2e_us"] == 8.0
    assert rec["dominant"] == "finalize_visible"


def test_device_anatomy_eviction_and_discard_leak_free():
    a = DeviceAnatomy(metrics=Metrics(), clock=_FakeClock(), capacity=4)
    for tid in range(1, 8):
        a.open(tid, t_enq=10)
    assert len(a._recs) == 4  # oldest evicted, never grows past capacity
    a.discard(7)
    a.discard(999)  # unknown: no-op
    assert 7 not in a._recs
    a.finish(6)
    assert a.slowest()  # the survivor folded


def test_null_device_anatomy_is_inert():
    assert NULL_DEVICE_ANATOMY.open(5, t_enq=1) == 0
    NULL_DEVICE_ANATOMY.stamp(5, DLEG_BUSY)
    NULL_DEVICE_ANATOMY.finish(5)
    assert NULL_DEVICE_ANATOMY.slowest() == []


def test_device_metric_names_cataloged():
    for leg in DEVICE_LEGS:
        name = f"device.{leg}_us"
        assert name in CATALOG, name
        kind, unit, help_ = CATALOG[name]
        assert kind == "histogram" and unit == "us" and help_
    for name, want_kind in (
        ("device.apply_e2e_us", "histogram"),
        ("device.samples", "counter"),
        ("device.queue_depth", "gauge"),
        ("device.h2d_bytes", "counter"),
        ("device.dispatches", "counter"),
        ("device.compiles", "counter"),
        ("device.compiles_post_warmup", "counter"),
        ("device.compile_ms", "histogram"),
        ("device.trace_windows", "counter"),
    ):
        assert name in CATALOG, name
        kind, unit, help_ = CATALOG[name]
        assert kind == want_kind and help_


# -- compile sentinel --------------------------------------------------


def test_compile_sentinel_counts_cold_cached_and_post_warmup():
    import jax.numpy as jnp

    from tigerbeetle_tpu.models.ledger import (
        COMPILE_SENTINEL,
        sentinel_jit,
    )

    was_warm = COMPILE_SENTINEL.warm
    try:
        COMPILE_SENTINEL.warm = False
        fn = sentinel_jit("test_sentinel_probe",
                          lambda x: x * 2 + jnp.sum(x))
        base = COMPILE_SENTINEL.per_name.get("test_sentinel_probe", 0)
        fn(jnp.arange(8))
        assert COMPILE_SENTINEL.per_name["test_sentinel_probe"] == base + 1
        fn(jnp.arange(8))  # cache hit: no growth, not a compile
        assert COMPILE_SENTINEL.per_name["test_sentinel_probe"] == base + 1
        post0 = COMPILE_SENTINEL.post_warmup
        COMPILE_SENTINEL.mark_warm()
        fn(jnp.arange(16))  # new shape AFTER warm: hot-path event
        assert COMPILE_SENTINEL.per_name["test_sentinel_probe"] == base + 2
        assert COMPILE_SENTINEL.post_warmup == post0 + 1
        snap = COMPILE_SENTINEL.snapshot()
        assert snap["total"] >= 2
        ev = [e for e in snap["events"]
              if e["fn"] == "test_sentinel_probe"]
        assert ev and ev[-1]["post_warmup"] is True
        assert ev[-1]["ms"] > 0
    finally:
        COMPILE_SENTINEL.warm = was_warm


def test_compile_sentinel_instrument_carries_totals():
    from tigerbeetle_tpu.models.ledger import COMPILE_SENTINEL

    m = Metrics()
    COMPILE_SENTINEL.instrument(m)
    snap = m.snapshot()
    # the fresh registry starts at zero; the process-wide totals carry in
    assert snap["counters"]["device.compiles"] == COMPILE_SENTINEL.total
    assert (snap["counters"]["device.compiles_post_warmup"]
            == COMPILE_SENTINEL.post_warmup)


def test_sentinel_jit_passes_through_non_jit_callables():
    from tigerbeetle_tpu.models.ledger import _SentinelJit

    calls = []
    wrapped = _SentinelJit(lambda x: calls.append(x) or x + 1,
                           "test_double")
    assert wrapped(41) == 42  # no _cache_size: plain passthrough
    assert calls == [41]


# -- live follower: stall -> queue_wait dominant; partition exactness --


def _valid_transfers(start: int, n: int) -> np.ndarray:
    x = np.zeros(n, dtype=types.TRANSFER_DTYPE)
    x["id_lo"] = np.arange(start, start + n, dtype=np.uint64)
    x["debit_account_id_lo"] = 1 + np.arange(n) % 9
    x["credit_account_id_lo"] = 1 + (np.arange(n) + 1) % 9
    x["amount_lo"] = 1
    x["ledger"] = 1
    x["code"] = 1
    return x


def _drive_sampled(led, op, arr, op_no: int) -> None:
    """The replica's commit-finalize seam with the op SAMPLED (lat_ns
    stamped), so every item opens a device-anatomy record."""
    led.prepare(op, len(arr))
    ts = led.prepare_timestamp
    p = led.execute_async(op, ts, arr)
    led.drain(p)
    led.apply_commit(op_no, op, ts, arr, p.codes,
                     prepare_checksum=0xABCD_0000 + op_no,
                     trace=0xD000_0000 + op_no,
                     lat_ns=perf_counter_ns())


def _acc_batch(start: int, n: int = 16) -> np.ndarray:
    acc = np.zeros(n, dtype=types.ACCOUNT_DTYPE)
    acc["id_lo"] = np.arange(start, start + n, dtype=np.uint64)
    acc["ledger"] = 1
    acc["code"] = 1
    return acc


def test_follower_stall_names_queue_wait_dominant_and_watch_columns():
    from tigerbeetle_tpu.inspect import _watch_line
    from tigerbeetle_tpu.metrics import FlightRecorder
    from tigerbeetle_tpu.models.dual_ledger import DualLedger

    led = DualLedger(12, 14, follower=True)
    led.instrument(Metrics(), NULL_TRACER)
    # warm round on a throwaway registry: the solo-apply kernels compile
    # here, so the stall round below measures a WARM applier (a cold
    # compile inside dispatch would otherwise drown the stall signal —
    # which is exactly what the compile sentinel exists to flag)
    _drive_sampled(led, Operation.create_accounts, _acc_batch(1), 1)
    assert led.drain_applier(500)
    m = Metrics()
    led.instrument(m, NULL_TRACER)
    fr = FlightRecorder(m)
    fr.record(1.0)  # baseline entry (deltas need a predecessor)
    # stall the apply loop and queue NON-coalescable ops (accounts runs
    # never fuse): each op waits behind every earlier op's stalled run,
    # so queue_wait accumulates quadratically while coalesce_hold pays
    # only its own run's stall — queue_wait must dominate
    led._test_apply_delay_s = 0.2
    for g in range(6):
        _drive_sampled(led, Operation.create_accounts,
                       _acc_batch(100 + 16 * g), 2 + g)
    led._test_apply_delay_s = 0.0
    report = led.finalize(timeout=500)
    assert report["verified"] is True, report
    snap = m.snapshot()
    assert snap["counters"]["device.samples"] == 6
    leg, share = dominant_leg({}, device_leg_totals(snap))
    assert leg == "queue_wait", (leg, device_leg_totals(snap))
    assert share > 0.3
    # the slowest record agrees and accounts for its span exactly
    rec = led.device_anatomy.slowest()[0]
    assert rec["dominant"] == "queue_wait", rec
    assert abs(sum(rec["legs"].values()) - rec["e2e_us"]) <= 0.01, rec
    # flight entry -> --watch line: the device columns surfaced
    entry = fr.record(2.0)
    line = _watch_line(entry)
    assert "dev_dominant=queue_wait" in line, line
    assert "disp/s=" in line, line
    assert "h2d=" in line or "dev_busy_p99=" in line, line
    # counters that feed the columns really moved
    assert snap["counters"]["device.dispatches"] >= 1
    assert snap["counters"]["device.h2d_bytes"] > 0


def test_follower_partition_exactness_all_sampled_no_stall():
    from tigerbeetle_tpu.models.dual_ledger import DualLedger

    m = Metrics()
    led = DualLedger(12, 14, follower=True)
    led.instrument(m, NULL_TRACER)
    acc = np.zeros(16, dtype=types.ACCOUNT_DTYPE)
    acc["id_lo"] = np.arange(1, 17, dtype=np.uint64)
    acc["ledger"] = 1
    acc["code"] = 1
    _drive_sampled(led, Operation.create_accounts, acc, 1)
    for g in range(3):
        _drive_sampled(led, Operation.create_transfers,
                       _valid_transfers(2000 + 32 * g, 32), 2 + g)
    report = led.finalize(timeout=500)
    assert report["verified"] is True, report
    snap = m.snapshot()
    assert snap["counters"]["device.samples"] == 4
    assert snap["histograms"]["device.apply_e2e_us"]["count"] == 4
    for rec in led.device_anatomy.slowest():
        assert abs(sum(rec["legs"].values()) - rec["e2e_us"]) <= 0.01, rec
        assert rec["dominant"] in rec["legs"]
    # histogram-level accounting: sum of sub-leg totals == e2e total
    totals = device_leg_totals(snap)
    h = snap["histograms"]["device.apply_e2e_us"]
    sub = sum(v["total_us"] for v in totals.values())
    e2e = h["count"] * h["mean"]
    assert abs(sub - e2e) / e2e < 1e-6, (sub, e2e)


def test_same_seed_follower_device_digests_identical_with_stamping():
    """Device stamping is observability, never state: two identical
    follower runs with EVERY op sampled produce identical device
    code-stream digests (and each verifies against native)."""
    from tigerbeetle_tpu.models.dual_ledger import DualLedger

    digests = []
    for _run in range(2):
        led = DualLedger(12, 14, follower=True)
        led.instrument(Metrics(), NULL_TRACER)
        acc = np.zeros(16, dtype=types.ACCOUNT_DTYPE)
        acc["id_lo"] = np.arange(1, 17, dtype=np.uint64)
        acc["ledger"] = 1
        acc["code"] = 1
        _drive_sampled(led, Operation.create_accounts, acc, 1)
        for g in range(3):
            _drive_sampled(led, Operation.create_transfers,
                           _valid_transfers(3000 + 32 * g, 32), 2 + g)
        report = led.finalize(timeout=500)
        assert report["verified"] is True, report
        digests.append(report["code_stream_digest"]["device"])
    assert digests[0] == digests[1]


# -- XLA trace bridge (stitch_trace --device-trace) --------------------


def _fake_profiler_dump(root, anchor_perf_ns: int):
    prof = root / "plugins" / "profile" / "2026_08_07_00_00_00"
    prof.mkdir(parents=True)
    events = [
        {"ph": "M", "name": "process_name", "pid": 5, "tid": 0,
         "args": {"name": "/device:TPU:0"}},
        {"ph": "X", "name": "fused_fold", "pid": 5, "tid": 1,
         "ts": 1000.0, "dur": 50.0},
        {"ph": "X", "name": "copy_h2d", "pid": 9, "tid": 0,
         "ts": 1200.0, "dur": 10.0},
    ]
    with gzip.open(prof / "host.trace.json.gz", "wt") as f:
        json.dump({"traceEvents": events}, f)
    (root / "device_trace_meta.json").write_text(json.dumps({
        "anchor_perf_ns": anchor_perf_ns,
        "anchor_unix_s": 0.0,
        "window_s": 1.0,
    }))


def test_stitch_load_device_trace_aligns_clock_and_repids(tmp_path):
    import sys as _sys

    _sys.path.insert(0, "/root/repo")
    from scripts.stitch_trace import load_device_trace

    _fake_profiler_dump(tmp_path, anchor_perf_ns=2_000_000_000)
    out = load_device_trace(str(tmp_path), pid_base=3)
    xs = [e for e in out if e.get("ph") == "X"]
    assert len(xs) == 2
    # earliest device ts lands ON the anchor (2e9 ns -> 2e6 us); the
    # second event keeps its relative offset
    by_name = {e["name"]: e for e in xs}
    assert by_name["fused_fold"]["ts"] == 2_000_000.0
    assert by_name["copy_h2d"]["ts"] == 2_000_200.0
    # device pids re-based after the span-dump pids, order-stable
    assert by_name["fused_fold"]["pid"] == 3
    assert by_name["copy_h2d"]["pid"] == 4
    # the profiler's own process_name metadata rode along, re-pid'd
    metas = [e for e in out if e.get("ph") == "M"]
    assert any(e["pid"] == 3 and e["args"]["name"] == "/device:TPU:0"
               for e in metas)
    # and the bridge stamped its own clock-caveat process label
    assert any("clock-aligned" in e["args"]["name"] for e in metas)


def test_stitch_device_trace_merges_with_span_dump(tmp_path):
    import sys as _sys

    _sys.path.insert(0, "/root/repo")
    from scripts.stitch_trace import load_device_trace
    from tigerbeetle_tpu.tracer import stitch

    _fake_profiler_dump(tmp_path, anchor_perf_ns=5_000_000_000)
    spans = [{"name": "shadow.upload", "ph": "X", "ts": 4_999_000.0,
              "dur": 3000.0, "pid": 0, "tid": 0, "args": {"trace": 7}}]
    merged = stitch([spans], labels=["applier"])
    dev = load_device_trace(str(tmp_path), pid_base=1)
    merged.extend(dev)
    pids = {e["pid"] for e in merged}
    assert 0 in pids and 1 in pids  # spans pid 0, device group after
    # device events sit inside the applier span's window after alignment
    span = next(e for e in merged if e.get("name") == "shadow.upload")
    fold = next(e for e in merged if e.get("name") == "fused_fold")
    assert span["ts"] <= fold["ts"] <= span["ts"] + span["dur"]


def test_load_device_trace_empty_dir_returns_nothing(tmp_path):
    import sys as _sys

    _sys.path.insert(0, "/root/repo")
    from scripts.stitch_trace import load_device_trace

    assert load_device_trace(str(tmp_path), pid_base=1) == []
