"""Bit-exact parity: the SHARDED ledger vs. the oracle on the 8-device mesh.

The sharded analog of tests/test_ledger_parity.py (reference model:
src/state_machine.zig semantics; sharding itself has no reference analog —
SURVEY.md §2.6). Exercises both tiers: the vectorized fast tier on clean
batches and the sharded serial tier (per-step psum lookups, ownership-masked
writes, chain rollback) on hazard batches.
"""

import jax
import numpy as np
import pytest
from jax.sharding import Mesh

from tigerbeetle_tpu.constants import ConfigProcess
from tigerbeetle_tpu.models.oracle import OracleStateMachine
from tigerbeetle_tpu.parallel.mesh import ShardedLedger
from tigerbeetle_tpu.testing.workload import WorkloadGenerator
from tigerbeetle_tpu.types import Account, Operation, Transfer, TransferFlags

PROCESS = ConfigProcess(account_slots_log2=10, transfer_slots_log2=12)


@pytest.fixture(scope="module")
def mesh():
    devices = jax.devices()[:8]
    assert len(devices) == 8, "conftest must provide 8 virtual CPU devices"
    return Mesh(np.array(devices), ("shard",))


def run_parity(mesh, seed, n_batches, batch_size, state_every=4, **wl_kwargs):
    oracle = OracleStateMachine()
    dev = ShardedLedger(mesh, PROCESS)
    gen = WorkloadGenerator(seed, **wl_kwargs)
    ts = 1_000_000_000
    for b in range(n_batches):
        if b % 4 == 0:
            op, events = gen.gen_accounts_batch(batch_size)
        else:
            op, events = gen.gen_transfers_batch(batch_size)
        ts += len(events)
        dense_o = oracle.execute_dense(op, ts, events)
        dense_d = dev.execute_dense(op, ts, events)
        if dense_d != dense_o:
            diffs = [
                (i, o, d) for i, (o, d) in enumerate(zip(dense_o, dense_d)) if o != d
            ]
            raise AssertionError(f"batch {b} ({op.name}): (idx, oracle, dev) {diffs[:10]}")
        if b % state_every == state_every - 1:
            accounts, transfers, posted = dev.extract()
            assert accounts == oracle.accounts, f"batch {b}: account state diverged"
            assert transfers == oracle.transfers, f"batch {b}: transfer state diverged"
            assert posted == oracle.posted, f"batch {b}: posted state diverged"
            assert dev.commit_timestamp == oracle.commit_timestamp
    return oracle, dev


@pytest.mark.parametrize("seed", [11, 12])
def test_sharded_parity_hazard_workload(mesh, seed):
    """Randomized workload with chains/two-phase/balancing/limits — routes
    through the sharded SERIAL tier."""
    run_parity(mesh, seed, n_batches=8, batch_size=32)


def test_sharded_parity_clean_workload(mesh):
    """Hazard-free workload — stays on the vectorized fast tier."""
    run_parity(
        mesh, 13, n_batches=8, batch_size=32,
        chain_rate=0.0, two_phase_rate=0.0, balancing_rate=0.0,
        limit_account_rate=0.0, conflict_rate=0.0,
    )


def test_sharded_lookup_parity(mesh):
    oracle, dev = run_parity(mesh, 14, n_batches=6, batch_size=24, state_every=100)
    gen = WorkloadGenerator(99)
    gen.account_ids = list(oracle.accounts.keys())[:40]
    gen.transfer_ids = list(oracle.transfers.keys())[:40]
    _, ids_a = gen.gen_lookup_batch(32, "accounts")
    _, ids_t = gen.gen_lookup_batch(32, "transfers")
    assert dev.lookup_accounts(ids_a) == oracle.lookup_accounts(ids_a)
    assert dev.lookup_transfers(ids_t) == oracle.lookup_transfers(ids_t)


def test_sharded_linked_chain_rollback(mesh):
    """Directed: a mid-batch chain break must roll back every shard's writes
    (cross-shard undo via per-shard slot logs)."""
    oracle = OracleStateMachine()
    dev = ShardedLedger(mesh, PROCESS)
    ts = 10_000
    accounts = [Account(id=i, ledger=1, code=1) for i in (1, 2, 3)]
    ts += 3
    assert oracle.execute_dense(Operation.create_accounts, ts, accounts) == \
        dev.execute_dense(Operation.create_accounts, ts, accounts)

    transfers = [
        Transfer(id=10, debit_account_id=1, credit_account_id=2, amount=5,
                 ledger=1, code=1, flags=1),
        Transfer(id=11, debit_account_id=2, credit_account_id=3, amount=7,
                 ledger=1, code=1, flags=1),
        Transfer(id=12, debit_account_id=1, credit_account_id=3, amount=0,
                 ledger=1, code=1),
        Transfer(id=13, debit_account_id=1, credit_account_id=2, amount=9,
                 ledger=1, code=1),
    ]
    ts += 4
    dense_o = oracle.execute_dense(Operation.create_transfers, ts, transfers)
    dense_d = dev.execute_dense(Operation.create_transfers, ts, transfers)
    assert dense_o == [1, 1, 18, 0]
    assert dense_d == dense_o
    accounts_d, transfers_d, _ = dev.extract()
    assert accounts_d == oracle.accounts
    assert transfers_d == oracle.transfers
    assert 13 in transfers_d and 10 not in transfers_d


def test_sharded_two_phase(mesh):
    """Directed: pending + post + void across shards (fulfill column lives on
    the pending transfer's owner shard)."""
    oracle = OracleStateMachine()
    dev = ShardedLedger(mesh, PROCESS)
    ts = 10_000
    accounts = [Account(id=i, ledger=1, code=1) for i in (1, 2)]
    ts += 2
    oracle.execute_dense(Operation.create_accounts, ts, accounts)
    dev.execute_dense(Operation.create_accounts, ts, accounts)

    transfers = [
        Transfer(id=20, debit_account_id=1, credit_account_id=2, amount=100,
                 ledger=1, code=1, flags=int(TransferFlags.pending)),
        Transfer(id=21, pending_id=20, amount=60, ledger=0, code=0,
                 flags=int(TransferFlags.post_pending_transfer)),
        Transfer(id=22, pending_id=20, ledger=0, code=0,
                 flags=int(TransferFlags.void_pending_transfer)),
    ]
    ts += 3
    dense_o = oracle.execute_dense(Operation.create_transfers, ts, transfers)
    dense_d = dev.execute_dense(Operation.create_transfers, ts, transfers)
    assert dense_o == [0, 0, 33]  # pending_transfer_already_posted
    assert dense_d == dense_o
    accounts_d, transfers_d, posted_d = dev.extract()
    assert accounts_d == oracle.accounts
    assert transfers_d == oracle.transfers
    assert posted_d == oracle.posted


def test_sharded_combined_overflow(mesh):
    """The combined dp+dpo overflow (codes 51/52) must be exact on the
    sharded ledger too: the host's amount-sum bound routes the batch to the
    sharded serial tier, which computes code 51."""
    oracle = OracleStateMachine()
    dev = ShardedLedger(mesh, PROCESS)
    ts = 10_000
    accounts = [Account(id=i, ledger=1, code=1) for i in (1, 2)]
    ts += 2
    oracle.execute_dense(Operation.create_accounts, ts, accounts)
    dev.execute_dense(Operation.create_accounts, ts, accounts)

    big = 1 << 127
    transfers = [
        Transfer(id=40, debit_account_id=1, credit_account_id=2, amount=big,
                 ledger=1, code=1, flags=int(TransferFlags.pending)),
        Transfer(id=41, debit_account_id=1, credit_account_id=2, amount=big,
                 ledger=1, code=1),
    ]
    ts += 2
    dense_o = oracle.execute_dense(Operation.create_transfers, ts, transfers)
    dense_d = dev.execute_dense(Operation.create_transfers, ts, transfers)
    assert dense_o == [0, 51]  # overflows_debits
    assert dense_d == dense_o
    accounts_d, transfers_d, _ = dev.extract()
    assert accounts_d == oracle.accounts
    assert transfers_d == oracle.transfers


def test_owner_hash_host_device_parity():
    """The host occupancy guard and the device kernels must agree on key
    ownership — drift re-exposes the silent shard-overflow the guard exists
    to prevent."""
    import jax.numpy as jnp

    from tigerbeetle_tpu.parallel.mesh import owner_of_ids_np, owner_of_key4

    rng = np.random.default_rng(3)
    lo = rng.integers(0, 1 << 63, size=256, dtype=np.uint64)
    hi = rng.integers(0, 1 << 63, size=256, dtype=np.uint64)
    k4 = np.stack(
        [lo & 0xFFFFFFFF, lo >> 32, hi & 0xFFFFFFFF, hi >> 32], axis=1
    ).astype(np.uint32)
    for n_shards in (2, 7, 8):
        dev = np.asarray(owner_of_key4(jnp.asarray(k4), n_shards))
        host = owner_of_ids_np(lo, hi, n_shards)
        assert (dev == host).all(), n_shards


def test_applied_insert_mask():
    """Occupancy reconciliation counts rolled-back chain inserts (they leave
    tombstones that still lengthen probe chains)."""
    from tigerbeetle_tpu.models.ledger import applied_insert_mask

    # standalone ok / standalone fail
    m = applied_insert_mask([0, 21], np.array([0, 0], dtype=np.uint16))
    assert list(m) == [True, False]
    # broken chain [1, 1, breaker, 1] + trailing standalone ok:
    # members before the breaker were applied then rolled back.
    flags = np.array([1, 1, 1, 1, 0], dtype=np.uint16)  # chain of 5? no:
    # linked,linked,linked,linked,plain -> one chain of 5, breaker at idx 2
    m = applied_insert_mask([1, 1, 18, 1, 1], flags)
    assert list(m) == [True, True, False, False, False]
    # unbroken chain: all applied
    m = applied_insert_mask([0, 0, 0], np.array([1, 1, 0], dtype=np.uint16))
    assert list(m) == [True, True, True]
    # chain_open at batch end (code 2 is the breaker)
    m = applied_insert_mask([1, 2], np.array([1, 1], dtype=np.uint16))
    assert list(m) == [True, False]


def test_sharded_wire_state_machine(mesh):
    """The wire-level StateMachine runs unchanged on the sharded backend."""
    from tigerbeetle_tpu import types
    from tigerbeetle_tpu.state_machine import StateMachine, encode_ids

    sm_o = StateMachine(OracleStateMachine())
    sm_d = StateMachine(ShardedLedger(mesh, PROCESS))
    accounts = [Account(id=i, ledger=1, code=1) for i in (1, 2)]
    body = types.accounts_to_np(accounts).tobytes()
    for sm in (sm_o, sm_d):
        sm.prepare(Operation.create_accounts, body)
    ts = sm_d.prepare_timestamp
    assert ts == sm_o.prepare_timestamp == 2
    assert sm_o.commit(Operation.create_accounts, ts, body) == \
        sm_d.commit(Operation.create_accounts, ts, body) == b""
    xfers = [Transfer(id=10, debit_account_id=1, credit_account_id=2,
                      amount=7, ledger=1, code=1)]
    body = types.transfers_to_np(xfers).tobytes()
    for sm in (sm_o, sm_d):
        sm.prepare(Operation.create_transfers, body)
    ts = sm_d.prepare_timestamp
    assert sm_o.commit(Operation.create_transfers, ts, body) == \
        sm_d.commit(Operation.create_transfers, ts, body) == b""
    look = encode_ids([1, 2, 3])
    assert sm_o.commit(Operation.lookup_accounts, ts, look) == \
        sm_d.commit(Operation.lookup_accounts, ts, look)


def test_sharded_load_guard(mesh):
    """The per-shard occupancy guard fails loudly before any shard's local
    table can exceed its load-factor cap (owner-hash skew means one shard
    fills first)."""
    small = ConfigProcess(account_slots_log2=4, transfer_slots_log2=6)
    dev = ShardedLedger(Mesh(np.array(jax.devices()[:2]), ("shard",)), small)
    accounts = [Account(id=i, ledger=1, code=1) for i in range(1, 40)]
    with pytest.raises(RuntimeError, match="load-factor"):
        dev.execute_dense(Operation.create_accounts, 100, accounts)


def test_sharded_chain_rollback_spans_shards(mesh):
    """Directed cross-SHARD rollback (VERDICT #9 leftover): the chain's
    accounts AND its transfer rows are placed on provably distinct shards
    (owner-hash verified), a mid-chain failure rolls back balance updates
    and row inserts on every shard it touched, and a follow-up batch
    proves the rolled-back state is live (not just extract-consistent)."""
    import numpy as np

    from tigerbeetle_tpu.parallel.mesh import owner_of_ids_np

    n_shards = 8

    def owner(id_):
        return int(owner_of_ids_np(
            np.array([id_ & ((1 << 64) - 1)], dtype=np.uint64),
            np.array([id_ >> 64], dtype=np.uint64),
            n_shards,
        )[0])

    # three accounts on three DISTINCT shards
    acct_ids, seen = [], set()
    i = 1
    while len(acct_ids) < 3:
        if owner(i) not in seen:
            seen.add(owner(i))
            acct_ids.append(i)
        i += 1
    a1, a2, a3 = acct_ids
    # chain transfer ids on two further distinct shards from each other
    t_ids, seen_t = [], set()
    i = 1000
    while len(t_ids) < 3:
        if owner(i) not in seen_t:
            seen_t.add(owner(i))
            t_ids.append(i)
        i += 1
    assert len(seen) == 3 and len(seen_t) == 3  # the rollback spans shards

    oracle = OracleStateMachine()
    dev = ShardedLedger(mesh, PROCESS)
    ts = 50_000
    accounts = [Account(id=i, ledger=1, code=1) for i in acct_ids]
    ts += 3
    assert oracle.execute_dense(Operation.create_accounts, ts, accounts) == \
        dev.execute_dense(Operation.create_accounts, ts, accounts)

    # linked chain across the three shards; the LAST link fails (amount=0
    # -> exceeds budget rules per the reference's zero-amount semantics),
    # so the two earlier APPLIED events must roll back on THEIR shards
    transfers = [
        Transfer(id=t_ids[0], debit_account_id=a1, credit_account_id=a2,
                 amount=5, ledger=1, code=1, flags=1),
        Transfer(id=t_ids[1], debit_account_id=a2, credit_account_id=a3,
                 amount=7, ledger=1, code=1, flags=1),
        Transfer(id=t_ids[2], debit_account_id=a3, credit_account_id=a1,
                 amount=0, ledger=1, code=1),  # chain terminator, fails
    ]
    ts += 3
    dense_o = oracle.execute_dense(Operation.create_transfers, ts, transfers)
    dense_d = dev.execute_dense(Operation.create_transfers, ts, transfers)
    assert dense_d == dense_o
    assert dense_o[0] != 0 and dense_o[1] != 0, (
        "chain members must report the rollback"
    )
    accounts_d, transfers_d, _ = dev.extract()
    assert accounts_d == oracle.accounts
    assert transfers_d == oracle.transfers
    for t in t_ids:
        assert t not in transfers_d  # every shard's insert rolled back
    for a in acct_ids:  # every shard's balance update rolled back
        assert accounts_d[a].debits_posted == 0
        assert accounts_d[a].credits_posted == 0

    # the rolled-back state is LIVE: the same ids re-submit cleanly
    retry = [
        Transfer(id=t_ids[0], debit_account_id=a1, credit_account_id=a2,
                 amount=5, ledger=1, code=1),
    ]
    ts += 1
    assert oracle.execute_dense(Operation.create_transfers, ts, retry) == \
        dev.execute_dense(Operation.create_transfers, ts, retry) == [0]
    accounts_d, transfers_d, _ = dev.extract()
    assert accounts_d == oracle.accounts
    assert transfers_d == oracle.transfers
