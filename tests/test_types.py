"""Struct layout golden tests vs. the reference's extern struct byte layouts
(reference: src/tigerbeetle.zig:7-104)."""

import numpy as np

from tigerbeetle_tpu.constants import U128_MAX
from tigerbeetle_tpu import types
from tigerbeetle_tpu.types import (
    ACCOUNT_DTYPE,
    TRANSFER_DTYPE,
    Account,
    CreateAccountResult,
    CreateTransferResult,
    Transfer,
)


def test_sizes():
    assert ACCOUNT_DTYPE.itemsize == 128
    assert TRANSFER_DTYPE.itemsize == 128


def test_account_field_offsets():
    # reference src/tigerbeetle.zig:7-29 field order, no padding.
    offsets = {
        name: ACCOUNT_DTYPE.fields[name][1] for name in ACCOUNT_DTYPE.names
    }
    assert offsets["id_lo"] == 0
    assert offsets["id_hi"] == 8
    assert offsets["debits_pending_lo"] == 16
    assert offsets["debits_posted_lo"] == 32
    assert offsets["credits_pending_lo"] == 48
    assert offsets["credits_posted_lo"] == 64
    assert offsets["user_data_128_lo"] == 80
    assert offsets["user_data_64"] == 96
    assert offsets["user_data_32"] == 104
    assert offsets["reserved"] == 108
    assert offsets["ledger"] == 112
    assert offsets["code"] == 116
    assert offsets["flags"] == 118
    assert offsets["timestamp"] == 120


def test_transfer_field_offsets():
    # reference src/tigerbeetle.zig:64-89.
    offsets = {
        name: TRANSFER_DTYPE.fields[name][1] for name in TRANSFER_DTYPE.names
    }
    assert offsets["id_lo"] == 0
    assert offsets["debit_account_id_lo"] == 16
    assert offsets["credit_account_id_lo"] == 32
    assert offsets["amount_lo"] == 48
    assert offsets["pending_id_lo"] == 64
    assert offsets["user_data_128_lo"] == 80
    assert offsets["user_data_64"] == 96
    assert offsets["user_data_32"] == 104
    assert offsets["timeout"] == 108
    assert offsets["ledger"] == 112
    assert offsets["code"] == 116
    assert offsets["flags"] == 118
    assert offsets["timestamp"] == 120


def test_u128_split_join_roundtrip():
    for x in (0, 1, (1 << 64) - 1, 1 << 64, U128_MAX, 0xDEADBEEF << 77):
        lo, hi = types.split_u128(x)
        assert types.join_u128(lo, hi) == x


def test_account_np_roundtrip():
    a = Account(
        id=(123 << 64) | 456,
        debits_pending=U128_MAX - 1,
        credits_posted=7,
        user_data_128=0xABCDEF << 60,
        user_data_64=99,
        user_data_32=3,
        ledger=700,
        code=10,
        flags=3,
        timestamp=1234567,
    )
    row = a.to_np()[0]
    assert Account.from_np(row) == a


def test_transfer_np_roundtrip():
    t = Transfer(
        id=U128_MAX - 3,
        debit_account_id=1,
        credit_account_id=2,
        amount=(1 << 127) + 5,
        pending_id=42,
        user_data_64=8,
        timeout=30,
        ledger=1,
        code=5,
        flags=2,
        timestamp=999,
    )
    row = t.to_np()[0]
    assert Transfer.from_np(row) == t


def test_transfer_bytes_golden():
    # Byte-level golden: id=1, amount=2^64 (hi limb = 1), flags=pending.
    t = Transfer(id=1, debit_account_id=2, credit_account_id=3, amount=1 << 64,
                 ledger=1, code=1, flags=2)
    raw = t.to_np().tobytes()
    assert len(raw) == 128
    assert raw[0:16] == (1).to_bytes(16, "little")
    assert raw[16:32] == (2).to_bytes(16, "little")
    assert raw[32:48] == (3).to_bytes(16, "little")
    assert raw[48:64] == (1 << 64).to_bytes(16, "little")
    assert raw[118:120] == (2).to_bytes(2, "little")  # flags
    assert raw[120:128] == (0).to_bytes(8, "little")


def test_result_enum_values():
    # Wire-protocol values (reference: src/tigerbeetle.zig:109-229).
    assert CreateAccountResult.exists == 21
    assert len(CreateAccountResult) == 22
    assert CreateTransferResult.exceeds_debits == 55
    assert len(CreateTransferResult) == 56
    assert CreateTransferResult.overflows_timeout == 53
    assert list(CreateTransferResult) == sorted(CreateTransferResult)


def test_flags_values():
    from tigerbeetle_tpu.types import AccountFlags, TransferFlags

    assert AccountFlags.linked == 1
    assert AccountFlags.debits_must_not_exceed_credits == 2
    assert AccountFlags.credits_must_not_exceed_debits == 4
    assert TransferFlags.pending == 2
    assert TransferFlags.post_pending_transfer == 4
    assert TransferFlags.void_pending_transfer == 8
    assert TransferFlags.balancing_debit == 16
    assert TransferFlags.balancing_credit == 32
    assert np.uint16(TransferFlags.padding_mask()) == 0xFFC0
