"""Continuous WAL repair in normal status + grid-zone faults under the
simulator (VERDICT r3 item 4).

The reference repairs faulty journal slots from peers during NORMAL
operation (reference: src/vsr/replica.zig:5248-5654) and its simulator
faults every storage zone under the fault-atlas rule (reference:
src/testing/storage.zig:1-25). Round 3 repaired prepares only inside
view-change adoption and never faulted the grid/forest zone.
"""


from tigerbeetle_tpu import types
from tigerbeetle_tpu.constants import ConfigProcess
from tigerbeetle_tpu.io.storage import Zone
from tigerbeetle_tpu.testing.cluster import Cluster
from tigerbeetle_tpu.testing.simulator import run_simulation
from tigerbeetle_tpu.testing.workload import WorkloadGenerator


def test_faulty_wal_slot_heals_in_normal_status():
    """A restarting replica whose recovery classifies a committed slot as
    TORN (body corrupt, redundant header intact) heals it via the
    normal-status WAL scrub — no view change, no commit needing the op."""
    cluster = Cluster(replica_count=3)
    client = cluster.add_client()
    gen = WorkloadGenerator(17)
    for _ in range(4):
        op, events = gen.gen_accounts_batch(12)
        cluster.execute(client, op, types.accounts_to_np(events).tobytes())
    victim = 2
    r = cluster.replicas[victim]
    committed = r.commit_min
    target = committed - 1  # committed long ago: nothing will re-commit it
    assert target >= 2
    slot = r.journal.slot_for_op(target)
    # corrupt the prepare BODY only (the redundant header survives -> the
    # recovery scan marks the slot faulty)
    cluster.storages[victim].fault(
        Zone.wal_prepares,
        slot * cluster.cluster_config.message_size_max + 256,
        64,
    )
    # the recovery scan classifies the slot TORN (faulty, repairable)
    from tigerbeetle_tpu.vsr.journal import Journal

    probe = Journal(cluster.storages[victim], cluster.cluster_config)
    probe.recover()
    assert probe.faulty.get(slot) == target
    assert probe.recover_stats["faulty"] >= 1

    view_before = cluster.replicas[0].view
    r2 = cluster.restart_replica(victim)
    cluster.run_ticks(40)  # scrub cadence fires; fills flow from peers
    assert r2.journal.read_prepare(target) is not None, (
        "faulty slot not repaired in normal status"
    )
    assert slot not in r2.journal.faulty
    assert cluster.replicas[0].view == view_before, (
        "repair must not need a view change"
    )
    assert r2.status == "normal"


def test_in_place_wal_fault_heals_via_slow_sweep():
    """Media corruption AFTER recovery (no restart): the round-robin
    sweep re-verifies live slots and refetches the broken one."""
    cluster = Cluster(replica_count=3)
    client = cluster.add_client()
    gen = WorkloadGenerator(19)
    for _ in range(3):
        op, events = gen.gen_accounts_batch(10)
        cluster.execute(client, op, types.accounts_to_np(events).tobytes())
    victim = 1
    r = cluster.replicas[victim]
    target = 2
    slot = r.journal.slot_for_op(target)
    cluster.storages[victim].fault(
        Zone.wal_prepares,
        slot * cluster.cluster_config.message_size_max + 300,
        64,
    )
    assert r.journal.read_prepare(target) is None
    # sweep pace: one op per WAL_SWEEP_TICKS; give it a full cycle
    from tigerbeetle_tpu.vsr.replica import WAL_SWEEP_TICKS

    cluster.run_ticks(WAL_SWEEP_TICKS * (r.op + 2) + 40)
    assert r.journal.read_prepare(target) is not None
    assert r.status == "normal"


def test_simulation_grid_zone_faults_heal():
    """Simulator seed with forest-block corruption under the atlas rule:
    spill-active replicas + mid-run grid faults + packet chaos must
    converge with bit-exact oracle parity (the final state check reads
    every spilled row through the grid)."""
    # tiny transfer table (limit 32 rows): the SECOND transfer batch
    # already spills, so forest blocks exist early in the (compile-bound,
    # slow) device-backend run and the fault injector finds targets
    stats = run_simulation(
        23,
        # 450 ticks: the client runtime's jittered retry ladder paces
        # this seed a little slower than the old flat resend cadence —
        # 300 ticks left it one committed batch short of the first spill
        # (no acquired forest blocks = no fault targets)
        ticks=450,
        backend_factory=None,  # DeviceLedger with forest (spill active)
        n_clients=1,
        client_batch=24,
        crash_probability=0.0,
        wal_fault_probability=0.0,
        torn_write_probability=0.0,
        replies_fault_probability=0.0,
        superblock_fault_probability=0.0,
        grid_fault_probability=0.15,
        forest_blocks=192,
        grid_size=64 * 1024 * 1024,
        # limit 64 rows: holds one 24-event batch's 2x admission need and
        # spills by the third transfer batch; tiny memtables flush spilled
        # rows into grid BLOCKS right away (fault targets exist mid-run)
        process=ConfigProcess(account_slots_log2=10, transfer_slots_log2=7,
                              lsm_memtable_max=48),
        # spill-heavy knobs (the default chaos mix mostly fails events and
        # never fills the table): one ledger, near-zero invalids/conflicts
        workload_knobs=dict(
            ledgers=(1,), invalid_rate=0.0, conflict_rate=0.03,
            chain_rate=0.0, two_phase_rate=0.1, balancing_rate=0.0,
            limit_account_rate=0.0,
        ),
    )
    assert stats["grid_faults"] >= 1, stats
    assert stats["committed_ops"] > 8, stats
