"""Live-cluster chaos (testing/chaos.py): real replica processes over
TCP, a multiplexed fleet on the client runtime, live faults, and the
three-way zero-lost/zero-duplicated verification.

The runs happen in a SUBPROCESS (scripts/chaos.py --json): the harness
drives sockets/signals/subprocess groups, and keeping all of that out
of the pytest process keeps this sandbox's documented XLA-CPU/native
fragility (see CHANGES.md, PRs 1-9) away from the in-process device
tests that run after this file.

The tier-1 smoke runs ONE kill/restart cycle (with the WAL disk-fault
flip) against a small native-backend cluster on CPU; the full storm —
1k sessions, dual backend, every fault class — is `slow` (it is also
the acceptance drive scripts/chaos.py runs standalone)."""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_chaos_cli(tmp_path, *args, timeout=600):
    report_path = tmp_path / "chaos_report.json"
    env = dict(os.environ, TB_JAX_PLATFORM="cpu")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "chaos.py"),
         "--json", str(report_path), *args],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=timeout,
    )
    assert proc.returncode == 0, (
        f"chaos run failed (rc {proc.returncode}):\n"
        f"{proc.stderr[-4000:]}\n{proc.stdout[-2000:]}"
    )
    with open(report_path) as f:
        return json.load(f)


def test_chaos_smoke_primary_kill_restart(tmp_path):
    """One SIGKILL of the primary under live multiplexed load, restart
    with a disk-fault flip: zero lost/duplicated transfers (client
    replies vs CDC vs wire conservation) and a recovery time reported —
    all client recovery driven by the runtime, no driver retries."""
    report = _run_chaos_cli(
        tmp_path,
        "--sessions", "12", "--conns", "2", "--accounts", "32",
        "--events-per-batch", "4", "--batches-per-session", "3",
        "--backend", "native", "--faults", "kill_primary",
        "--restart-after", "1.0", "--deadline", "240",
        timeout=420,
    )
    assert report["kills"] == 1
    assert report["restarts"] == 1
    assert report["lost_events"] == 0
    assert report["acked_events"] == 12 * 3 * 4
    assert report["conservation_ok"]
    assert report["disk_fault_slots"]  # the flip actually landed
    assert report["failover_recovery_ms"] is not None
    assert report["cdc"]["dup_ids"] == 0
    assert report["cdc"]["transfers_bad"] == 0
    # the fleet recovered through the RUNTIME: timeouts/resends fired
    assert report["client"]["timeouts"] > 0
    assert report["client"]["resends"] > 0


def test_chaos_kill_cluster_federation(tmp_path):
    """Region-level chaos (federation/live.py via --kill-cluster): two
    real 2-replica clusters with commitment chains and AOF-backed CDC
    tails, the live settlement agent posting mirror/resolve legs between
    them, EVERY replica of one region SIGKILLed mid-settlement and
    restarted from disk. Every origin pending settles (or voids — the
    bad-beneficiary slice), cross-region conservation holds pairwise,
    and each region's CDC stream replays clean against the commitment
    head its replica published at shutdown."""
    report = _run_chaos_cli(
        tmp_path,
        "--kill-cluster", "--replicas", "2", "--payments", "12",
        "--restart-after", "1.0", "--deadline", "300",
        timeout=420,
    )
    assert report["kills"] == 2 and report["restarts"] == 2
    assert report["region_killed"] in (0, 1)
    assert report["issued"] == 2 * 12
    assert report["settled"] + report["voided"] == report["issued"]
    assert report["voided"] == report["void_targets"]
    assert report["conservation"]["ok"]
    for r in ("0", "1"):
        assert report["stream_verify"][r]["checked"] > 0
        assert (report["stream_verify"][r]["head_op"]
                == report["commitment_heads"][r][0])


@pytest.mark.slow
def test_chaos_full_storm_dual_backend(tmp_path):
    """The acceptance drive: >= 1k multiplexed sessions against a
    3-replica `--backend dual` cluster, primary SIGKILL + SIGSTOP gray
    failure + backup kill + connection resets + a disk-fault restart —
    zero lost/duplicated transfers and per-replica device hash-log
    parity after the storm."""
    report = _run_chaos_cli(
        tmp_path,
        "--sessions", "1000", "--conns", "16", "--accounts", "256",
        "--events-per-batch", "4", "--batches-per-session", "3",
        "--backend", "dual",
        "--faults", "kill_primary,gray_primary,kill_backup,reset_conns",
        "--deadline", "900",
        timeout=1800,
    )
    assert report["kills"] == 2 and report["restarts"] == 2
    assert report["gray_stops"] == 1 and report["conn_resets"] == 1
    assert report["lost_events"] == 0
    assert report["conservation_ok"]
    assert report["cdc"]["dup_ids"] == 0
    assert report["failover_recovery_ms"] is not None
    for name, p in report["parity"].items():
        assert p["verified"], (name, p)
        assert p["hash_log_ok"] is not False, (name, p)
