"""Adversarial-reply pressure on the client side (reference:
src/vsr/client.zig:17-80 — the session client checksums every reply and
matches request numbers, so a Byzantine/stale/corrupt frame can never be
surfaced to the application).

A fake raw-socket "replica" feeds each client a corrupted-header reply, a
corrupted-body reply, a stale-request-number reply, and a truncated frame,
then the genuine reply — both the Python vsr client and the native C
client must surface ONLY the genuine one."""

import socket
import threading

from tigerbeetle_tpu.types import Operation
from tigerbeetle_tpu.vsr.header import HEADER_SIZE, Command, Header


def _reply(request: int, body: bytes, operation: int,
            corrupt_header: bool = False, corrupt_body: bool = False,
            view: int = 0) -> bytes:
    h = Header(
        command=int(Command.reply),
        operation=operation,
        request=request,
        view=view,
    )
    h.set_checksum_body(body)
    h.set_checksum()
    wire = bytearray(h.to_bytes() + body)
    if corrupt_header:
        wire[8] ^= 0xFF  # flips the header checksum field itself
    if corrupt_body and body:
        wire[HEADER_SIZE] ^= 0xFF  # body no longer matches checksum_body
    return bytes(wire)


class _FakeReplica:
    """Accepts one client connection and replays a scripted reply sequence
    for each request that arrives."""

    def __init__(self):
        self.sock = socket.socket()
        self.sock.bind(("127.0.0.1", 0))
        self.sock.listen(1)
        self.port = self.sock.getsockname()[1]
        self.script = []  # per-request: callable(request_header) -> [bytes]
        self.thread = threading.Thread(target=self._run, daemon=True)
        self.errors: list[Exception] = []

    def _read_exact(self, conn, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            got = conn.recv(n - len(buf))
            if not got:
                raise ConnectionError("client closed")
            buf += got
        return buf

    def _run(self):
        try:
            conn, _ = self.sock.accept()
            conn.settimeout(30)
            steps = list(self.script)
            while steps:
                raw = self._read_exact(conn, HEADER_SIZE)
                h = Header.from_bytes(raw)
                body = self._read_exact(conn, h.size - HEADER_SIZE)
                if h.command != Command.request:
                    continue  # bus hello frames etc.: not a request
                step = steps.pop(0)
                for wire in step(h, body):
                    conn.sendall(wire)
            conn.close()
        except Exception as e:  # surfaced by the test at join
            self.errors.append(e)

    def start(self):
        self.thread.start()

    def join(self):
        self.thread.join(timeout=30)
        self.sock.close()
        assert not self.errors, self.errors


def _scripted_session(adversarial_for_request_1):
    """Script: register succeeds cleanly; request 1 gets the adversarial
    barrage then the genuine reply."""
    session = 7

    def on_register(h, _body):
        return [_reply(0, session.to_bytes(8, "little"),
                       int(Operation.register))]

    def on_request(h, _body):
        return adversarial_for_request_1(h)

    return [on_register, on_request]


def _barrage(h):
    """Corrupt header, corrupt body, stale request number, truncated
    frame... then the genuine empty-body success reply."""
    genuine = _reply(h.request, b"", h.operation)
    stale = _reply(h.request - 1, b"\x01\x02\x03\x04\x05\x06\x07\x08",
                   h.operation)
    corrupt_h = _reply(h.request, b"", h.operation, corrupt_header=True)
    corrupt_b = _reply(h.request, b"\x00" * 8, h.operation,
                       corrupt_body=True)
    # Truncated FRAME: a header announcing 128+8 bytes but only 4 bytes of
    # body before the genuine reply follows — the stream recovers only if
    # the client's framing treats the checksum gate as authoritative.
    # (For stream transports a truncated frame shifts framing; both
    # clients recover because every candidate frame is checksum-gated.)
    trunc_h = Header(
        command=int(Command.reply), operation=h.operation, request=h.request
    )
    trunc_h.set_checksum_body(b"\xEE" * 8)
    trunc_h.set_checksum()
    truncated = trunc_h.to_bytes() + b"\xEE" * 4  # 4 bytes short
    pad = b"\x00" * 4  # realign the stream for the genuine frame
    return [corrupt_h, corrupt_b, stale, truncated + pad, genuine]


def test_python_client_rejects_adversarial_replies():
    from tigerbeetle_tpu.io.message_bus import TCPMessageBus
    from tigerbeetle_tpu.vsr.client import Client

    fake = _FakeReplica()
    fake.script = _scripted_session(_barrage)
    fake.start()

    bus = TCPMessageBus([("127.0.0.1", fake.port)], 0xADE1)
    client = Client(0xADE1, bus, replica_count=1)
    client.register()
    deadline = 200
    while client.reply is None and deadline:
        bus.pump(timeout=0.05)
        deadline -= 1
    assert client.reply is not None, "register reply lost"
    client.take_reply()
    assert client.session == 7

    client.request(Operation.create_accounts, b"\x00" * 128)
    deadline = 200
    while client.reply is None and deadline:
        bus.pump(timeout=0.05)
        deadline -= 1
    header, body = client.take_reply()
    # ONLY the genuine reply surfaced: empty body, matching request number
    assert body == b"" and header.request == 1
    fake.join()


def test_native_client_rejects_adversarial_replies():
    from tigerbeetle_tpu.client_ffi import NativeClient

    fake = _FakeReplica()
    fake.script = _scripted_session(_barrage)
    fake.start()

    client = NativeClient("127.0.0.1", fake.port)
    reply = client._request(Operation.create_accounts, b"\x00" * 128)
    assert reply == b""  # the stale 8-byte body never surfaced
    client.close()
    fake.join()


def test_python_client_ignores_wrong_command():
    """A non-reply command (e.g. a spoofed prepare) must not satisfy the
    in-flight request even with valid checksums."""
    from tigerbeetle_tpu.io.message_bus import TCPMessageBus
    from tigerbeetle_tpu.vsr.client import Client

    def barrage(h):
        spoof = Header(
            command=int(Command.prepare), operation=h.operation,
            request=h.request,
        )
        spoof.set_checksum_body(b"")
        spoof.set_checksum()
        return [spoof.to_bytes(), _reply(h.request, b"", h.operation)]

    fake = _FakeReplica()
    fake.script = _scripted_session(barrage)
    fake.start()

    bus = TCPMessageBus([("127.0.0.1", fake.port)], 0xADE2)
    client = Client(0xADE2, bus, replica_count=1)
    client.register()
    deadline = 200
    while client.reply is None and deadline:
        bus.pump(timeout=0.05)
        deadline -= 1
    client.take_reply()
    client.request(Operation.create_accounts, b"\x00" * 128)
    deadline = 200
    while client.reply is None and deadline:
        bus.pump(timeout=0.05)
        deadline -= 1
    header, body = client.take_reply()
    assert header.command == Command.reply and body == b""
    fake.join()
