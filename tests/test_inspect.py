"""`tigerbeetle inspect`: offline data-file + live-state introspection.

Tier-1 smoke contract (reference: src/tigerbeetle/inspect.zig): a freshly
formatted and briefly-driven data file decodes offline — superblock
copies with checksum verdicts, WAL ring slots (incl. a deliberately torn
tail, diagnosed with the slot class and the exact break op), client-reply
slots, the client table, checkpoint blobs — and a RUNNING server answers
`inspect live` with its [stats] registry snapshot over the wire.
"""

import json
import os
import signal
import socket
import subprocess
import sys
import time

import numpy as np

import tests.conftest  # noqa: F401 — CPU platform before jax init
from tigerbeetle_tpu import types
from tigerbeetle_tpu.constants import TEST_PROCESS, ConfigCluster
from tigerbeetle_tpu.io.network import InProcessNetwork
from tigerbeetle_tpu.io.storage import FileStorage, Zone, ZoneLayout
from tigerbeetle_tpu.io.time import DeterministicTime
from tigerbeetle_tpu.types import Operation

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _drive_data_file(path: str) -> tuple[ConfigCluster, int]:
    """Format + drive a single-replica (oracle backend) over a real
    FileStorage: register, accounts, transfers, a checkpoint, and one
    post-checkpoint op so the WAL carries a replayable tail. Returns
    (cluster config, head op)."""
    from tigerbeetle_tpu.cli import main
    from tigerbeetle_tpu.models.oracle import OracleStateMachine
    from tigerbeetle_tpu.vsr.client import Client
    from tigerbeetle_tpu.vsr.replica import Replica

    assert main(["format", "--cluster", "0", "--replica", "0",
                 "--replica-count", "1", path]) == 0
    cluster = ConfigCluster()
    layout = ZoneLayout(cluster, grid_size=64 * 1024 * 1024)
    storage = FileStorage(path, layout, create=False)
    net = InProcessNetwork()
    r = Replica(0, 1, storage, net, DeterministicTime(), cluster,
                TEST_PROCESS, backend_factory=OracleStateMachine)
    r.sync_payload_async = False
    r.open()
    c = Client(1 << 64, net, 1)
    c.register()
    net.run()
    c.take_reply()

    def execute(op, body):
        c.request(op, body)
        net.run()
        return c.take_reply()

    acct = np.zeros(2, dtype=types.ACCOUNT_DTYPE)
    acct["id_lo"] = [1, 2]
    acct["ledger"] = 1
    acct["code"] = 1
    execute(Operation.create_accounts, acct.tobytes())
    for i in range(3):
        t = np.zeros(1, dtype=types.TRANSFER_DTYPE)
        t["id_lo"] = 100 + i
        t["debit_account_id_lo"] = 1
        t["credit_account_id_lo"] = 2
        t["amount_lo"] = 1
        t["ledger"] = 1
        t["code"] = 1
        execute(Operation.create_transfers, t.tobytes())
    r.checkpoint()
    t = np.zeros(1, dtype=types.TRANSFER_DTYPE)
    t["id_lo"] = 999
    t["debit_account_id_lo"] = 1
    t["credit_account_id_lo"] = 2
    t["amount_lo"] = 1
    t["ledger"] = 1
    t["code"] = 1
    execute(Operation.create_transfers, t.tobytes())
    head = r.op
    storage.close()
    return cluster, head


def test_inspect_offline_decodes_a_driven_data_file(tmp_path, capsys):
    """The tier-1 smoke: every offline topic decodes a real formatted +
    driven file, and the reports carry the facts an operator would act
    on (quorum verdicts, replayable chain, sessions, blob checksums)."""
    from tigerbeetle_tpu import inspect as _inspect
    from tigerbeetle_tpu.cli import main

    path = str(tmp_path / "data.tb")
    cluster, head = _drive_data_file(path)

    storage = _inspect.open_storage(path, cluster)
    try:
        sb = _inspect.inspect_superblock(storage)
        assert sb["quorum"] is not None
        assert sb["quorum_copies"] == 4
        assert all(c["verdict"] == "valid" for c in sb["copies"])
        state = sb["state"]
        assert state.commit_min == head - 1  # checkpoint preceded last op

        wal = _inspect.inspect_wal(storage, cluster, state)
        assert wal["stats"]["valid"] == head  # every op journaled intact
        assert wal["chain_end"] == head
        assert wal["chain_break"] is None

        one = _inspect.inspect_wal_op(storage, cluster, head)
        assert one["verdict"] == "valid"
        assert one["header"]["operation"] == "create_transfers"
        assert one["body"]["events"] == 1
        assert int(one["trace"], 16) != 0  # the op's causal trace id

        replies = _inspect.inspect_replies(storage, cluster)
        assert len(replies["slots"]) == 1
        assert replies["slots"][0]["body_ok"] is True

        table = _inspect.inspect_client_table(storage, state)
        assert table["sessions"] == 1
        assert table["source"] == "inline"

        grid = _inspect.inspect_grid(storage, cluster, state)
        assert all(b["checksum_ok"] for b in grid["blobs"])
    finally:
        storage.close()

    # the CLI wiring end to end (text + --json)
    assert main(["inspect", "all", path]) == 0
    out = capsys.readouterr().out
    assert "quorum: sequence" in out
    assert "replayable chain" in out
    assert main(["inspect", "superblock", "--json", path]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["quorum"] is not None


def test_inspect_diagnoses_a_torn_wal_tail(tmp_path, capsys):
    """Tear the head op's prepare body (a crash mid-write): the WAL scan
    classifies the slot, and the chain diagnosis names the exact op and
    why — the `inspect` cookbook's first recipe."""
    from tigerbeetle_tpu import inspect as _inspect
    from tigerbeetle_tpu.cli import main

    path = str(tmp_path / "data.tb")
    cluster, head = _drive_data_file(path)

    layout = ZoneLayout(cluster, grid_size=64 * 1024 * 1024)
    storage = FileStorage(path, layout, create=False)
    slot = head % cluster.journal_slot_count
    raw = bytearray(storage.read(
        Zone.wal_prepares, slot * cluster.message_size_max, 4096
    ))
    for i in range(200, 264):
        raw[i] ^= 0xFF
    storage.write(Zone.wal_prepares, slot * cluster.message_size_max,
                  bytes(raw))
    storage.close()

    storage = _inspect.open_storage(path, cluster)
    try:
        state = _inspect.inspect_superblock(storage)["state"]
        wal = _inspect.inspect_wal(storage, cluster, state)
        assert wal["chain_end"] == head - 1
        assert wal["chain_break"] == {
            "op": head, "slot": slot, "why": "torn_prepare",
        }
        one = _inspect.inspect_wal_op(storage, cluster, head)
        assert one["verdict"] == "body checksum mismatch (torn)"
    finally:
        storage.close()

    assert main(["inspect", "wal", path]) == 0
    out = capsys.readouterr().out
    assert f"TORN TAIL: chain breaks at op {head}" in out


def test_inspect_diagnoses_a_misdirected_wal_write(tmp_path):
    """A checksum-VALID prepare that landed in the WRONG slot must not
    make the chain walk call the log replayable: recovery reads the
    op's own slot (stale/blank) and stops there — inspect must say so,
    and name the stray copy."""
    from tigerbeetle_tpu import inspect as _inspect

    path = str(tmp_path / "data.tb")
    cluster, head = _drive_data_file(path)

    layout = ZoneLayout(cluster, grid_size=64 * 1024 * 1024)
    storage = FileStorage(path, layout, create=False)
    msg_max = cluster.message_size_max
    slot = head % cluster.journal_slot_count
    wrong = (slot + 7) % cluster.journal_slot_count
    raw = storage.read(Zone.wal_prepares, slot * msg_max, msg_max)
    storage.write(Zone.wal_prepares, wrong * msg_max, raw)  # stray copy
    # the op's own slot loses its prepare AND its redundant header (the
    # misdirected-write shape: nothing landed where it should have);
    # header zeroing is a sector-aligned read-modify-write (O_DIRECT)
    storage.write(Zone.wal_prepares, slot * msg_max, b"\0" * 4096)
    hsec = slot * 128 // 4096 * 4096
    sector = bytearray(storage.read(Zone.wal_headers, hsec, 4096))
    off = slot * 128 - hsec
    sector[off : off + 128] = b"\0" * 128
    storage.write(Zone.wal_headers, hsec, bytes(sector))
    storage.close()

    storage = _inspect.open_storage(path, cluster)
    try:
        state = _inspect.inspect_superblock(storage)["state"]
        wal = _inspect.inspect_wal(storage, cluster, state)
        assert wal["stats"].get("misdirected") == 1
        assert wal["chain_end"] == head - 1  # NOT "replayable to head"
        assert wal["chain_break"] == {
            "op": head, "slot": wrong,
            "why": "misdirected (found in wrong slot)",
        }
    finally:
        storage.close()


def test_inspect_lsm_decodes_manifest_per_groove(tmp_path):
    """A checkpointed LSM forest's manifest decodes offline: tables per
    tree/level with entry counts and key ranges, named per groove."""
    from tigerbeetle_tpu import inspect as _inspect
    from tigerbeetle_tpu.lsm.grid import Grid
    from tigerbeetle_tpu.lsm.groove import Forest
    from tigerbeetle_tpu.vsr.superblock import SuperBlock, VSRState

    cluster = ConfigCluster()
    layout = ZoneLayout(cluster, grid_size=64 * 1024 * 1024,
                        forest_blocks=192)
    path = str(tmp_path / "lsm.tb")
    storage = FileStorage(path, layout, create=True)
    try:
        forest = Forest(Grid(
            storage, offset=layout.forest_offset, block_count=192,
        ), memtable_max=8)
        for ts in range(1, 33):  # spans several flushed tables
            forest.posted.put(ts.to_bytes(8, "big"), b"\x01")
        meta = {
            "manifest": forest.checkpoint(),
            "spilled_blocks": [],
            "spilled_count": 0,
        }
        sb = SuperBlock(storage)
        sb.checkpoint(VSRState(sequence=1, meta={"spill": meta}))

        state = _inspect.inspect_superblock(storage)["state"]
        lsm = _inspect.inspect_lsm(storage, cluster, state)
        assert lsm["manifest_events"] > 0
        posted = next(
            t for t in lsm["trees"] if t["name"] == "posted"
        )
        total = sum(lv["entries"] for lv in posted["levels"])
        assert total == 32
        grid_rep = _inspect.inspect_grid(storage, cluster, state)
        fs = grid_rep["free_set"]
        assert fs["acquired"] > 0 and fs["corrupt"] == []
    finally:
        storage.close()


def _spawn_server(tmp_path):
    env = dict(os.environ, PYTHONPATH=REPO, TB_JAX_PLATFORM="cpu",
               TB_PARENT_WATCHDOG="1")
    path = str(tmp_path / "live.tb")
    fmt = subprocess.run(
        [sys.executable, "-m", "tigerbeetle_tpu", "format",
         "--cluster", "0", "--replica", "0", "--replica-count", "1",
         path],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=120,
    )
    assert fmt.returncode == 0, fmt.stderr
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    proc = subprocess.Popen(
        [sys.executable, "-m", "tigerbeetle_tpu", "start",
         "--addresses", f"127.0.0.1:{port}",
         "--backend", "native",
         "--account-slots-log2", "14", "--transfer-slots-log2", "14",
         path],
        cwd=REPO, env=env, start_new_session=True,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )
    while True:
        line = proc.stdout.readline()
        if not line:
            raise RuntimeError("server died before listening")
        if "listening" in line:
            return proc, port


def test_inspect_live_reads_running_server_stats(tmp_path):
    """`inspect live` pulls the [stats] registry snapshot off a running
    server socket; SIGQUIT dumps hang diagnosis WITHOUT killing the
    server; and the [stats] line at SIGTERM agrees with the wire
    snapshot's registry (same store)."""
    from tigerbeetle_tpu.inspect import inspect_live
    from tigerbeetle_tpu.metrics import CATALOG

    proc, port = _spawn_server(tmp_path)
    try:
        snap = inspect_live("127.0.0.1", port)
        assert snap["status"] == "normal"
        assert snap["replica"] == 0
        counters = snap["metrics"]["counters"]
        assert counters["inspect.live_requests"] == 1
        # hang diagnosis: SIGQUIT dumps and the server keeps serving
        os.kill(proc.pid, signal.SIGQUIT)
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            snap2 = inspect_live("127.0.0.1", port)
            if snap2["metrics"]["counters"].get("trace.sigquit_dumps"):
                break
            time.sleep(0.1)
        assert proc.poll() is None, "SIGQUIT must not kill the server"
        assert snap2["metrics"]["counters"]["trace.sigquit_dumps"] == 1
        # metric-catalog drift guard, against a REAL server snapshot:
        # every counter/gauge the server emits must be CATALOG'd
        # (tests/test_metrics.py enforces the same for each subsystem's
        # names; this is the end-to-end [stats] surface)
        emitted = set(snap2["metrics"]["counters"]) | set(
            snap2["metrics"]["gauges"]
        )
        missing = emitted - set(CATALOG)
        assert not missing, f"[stats] names missing from CATALOG: {missing}"
    finally:
        proc.terminate()
        out, _ = proc.communicate(timeout=60)
    # the SIGTERM [stats] line reads the same registry
    stats_line = next(
        line for line in out.splitlines() if line.startswith("[stats] ")
    )
    stats = json.loads(stats_line[8:])
    assert stats["metrics"]["counters"]["trace.sigquit_dumps"] == 1
    emitted = set(stats["metrics"]["counters"]) | set(
        stats["metrics"]["gauges"]
    )
    from tigerbeetle_tpu.metrics import CATALOG

    assert not emitted - set(CATALOG)
    # the SIGQUIT diagnosis reached stderr/stdout
    assert "[quit] status=" in out
    assert "Current thread" in out  # faulthandler stack snapshot


def test_inspect_live_watch_streams_flight_history(tmp_path):
    """The flight recorder's history rides the [stats] wire command and
    `inspect live --watch` renders it: per-interval delta entries, one
    rates line each (JSONL with --json), against the same any-status
    serving path as single-shot live. The SIGQUIT dump carries the
    history too — the whole incident-replay loop against one server."""
    import io

    from tigerbeetle_tpu.inspect import inspect_live, watch_live

    proc, port = _spawn_server(tmp_path)
    try:
        # wait for the recorder to take a couple of entries (~1/s)
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            snap = inspect_live("127.0.0.1", port)
            if len(snap.get("history") or []) >= 2:
                break
            time.sleep(0.3)
        history = snap.get("history")
        assert history and len(history) >= 2, "no flight history served"
        for e in history:
            assert "t" in e and "counters" in e and "gauges" in e
        assert history[1]["dt"] is not None
        # latency anatomy surfaces ride the same snapshot
        assert "latency_slowest" in snap
        assert "latency.e2e_us" in snap["metrics"]["histograms"]

        # watch mode: two polls, human lines then JSONL
        out = io.StringIO()
        rc = watch_live("127.0.0.1", port, interval_s=1.2, count=2,
                        out=out)
        assert rc == 0
        text = out.getvalue()
        assert "t=" in text and "ops/s=" in text, text
        out = io.StringIO()
        watch_live("127.0.0.1", port, interval_s=1.2, count=1, out=out,
                   as_json=True)
        lines = [ln for ln in out.getvalue().splitlines() if ln]
        assert lines, "json watch printed nothing"
        for ln in lines:
            assert "t" in json.loads(ln)

        # SIGQUIT: the hang dump must carry the history ring
        os.kill(proc.pid, signal.SIGQUIT)
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if inspect_live("127.0.0.1", port)["metrics"]["counters"].get(
                "trace.sigquit_dumps"
            ):
                break
            time.sleep(0.1)
        assert proc.poll() is None
    finally:
        proc.terminate()
        out_text, _ = proc.communicate(timeout=60)
    quit_line = next(
        ln for ln in out_text.splitlines() if ln.startswith("[quit] stats ")
    )
    quit_stats = json.loads(quit_line[len("[quit] stats "):])
    assert quit_stats.get("history"), "SIGQUIT dump lost the flight ring"
    assert "latency_slowest" in quit_stats
