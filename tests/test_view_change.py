"""View change, repair, and the cluster clock (reference:
src/vsr/replica.zig:1595-1924 view change; src/vsr/clock.zig Marzullo)."""


from tigerbeetle_tpu import types
from tigerbeetle_tpu.io.time import DeterministicTime
from tigerbeetle_tpu.testing.cluster import Cluster
from tigerbeetle_tpu.testing.state_checker import (
    assert_convergence,
    assert_identical_state,
)
from tigerbeetle_tpu.testing.workload import WorkloadGenerator
from tigerbeetle_tpu.types import Operation
from tigerbeetle_tpu.vsr.clock import Clock, marzullo


# ----------------------------------------------------------------------
# Marzullo / clock
# ----------------------------------------------------------------------


def test_marzullo_basic():
    # three sources agreeing around +10, one outlier
    iv = [(8, 12), (9, 13), (7, 11), (100, 104)]
    w = marzullo(iv, quorum=3)
    assert w is not None
    assert 9 <= w.lo <= w.hi <= 11
    # no quorum point
    assert marzullo([(0, 1), (10, 11), (20, 21)], quorum=2) is None
    # quorum of one is just the first-best overlap
    w = marzullo([(5, 6)], quorum=1)
    assert (w.lo, w.hi) == (5, 6)


def test_clock_synchronizes_against_skewed_peers():
    time = DeterministicTime(offset_ns=0)
    clock = Clock(0, 3, time)
    assert clock.realtime_synchronized() is None  # no samples yet
    # Peers are skewed +50ms and +60ms; RTT 2 ticks.
    time.ticks = 100
    m0 = time.monotonic()
    time.ticks += 2
    for peer, skew in ((1, 50_000_000), (2, 60_000_000)):
        t1 = time.realtime() - time.tick_ns + skew  # peer read mid-RTT
        clock.learn(peer, m0, t1, time.monotonic())
    rt = clock.realtime_synchronized()
    assert rt is not None
    # Synchronized time is own realtime + a learned offset within the skew
    # envelope (0 is in the quorum window since self is a source).
    assert 0 <= rt - time.realtime() <= 60_000_000


def test_cluster_clock_synchronizes_in_harness():
    """Ping/pong round trips within one tick still produce valid (zero
    width) offset intervals — the synchronized path must come alive."""
    cluster = Cluster(replica_count=3)
    cluster.run_ticks(20)
    for r in cluster.replicas:
        assert r.clock.realtime_synchronized() is not None, r.replica


def test_register_retransmit_no_second_session():
    """A duplicate register must answer from the table, not mint a second
    session that evicts the client."""
    cluster = Cluster(replica_count=3)
    client = cluster.add_client()
    session = client.session
    commit = cluster.replicas[0].commit_min
    # simulate a late retransmit of the original register
    reg = client.in_flight  # cleared — rebuild the register bytes
    from tigerbeetle_tpu.vsr.header import Command, Header

    h = Header(
        command=int(Command.request),
        operation=int(Operation.register),
        client=client.client_id,
        request=0,
    )
    h.set_checksum_body(b"")
    h.set_checksum()
    cluster.network.send(client.client_id, 0, h.to_bytes())
    cluster.network.run()
    assert cluster.replicas[0].commit_min == commit  # no second register op
    assert cluster.replicas[0].client_table[client.client_id]["session"] == session
    # and the client can still transact
    body = types.accounts_to_np([types.Account(id=5, ledger=1, code=1)]).tobytes()
    hreply, r = cluster.execute(client, Operation.create_accounts, body)
    assert r == b"" and not client.evicted


# ----------------------------------------------------------------------
# view change
# ----------------------------------------------------------------------


def _commit_batches(cluster, client, gen, n, start=0):
    committed = []
    for b in range(start, start + n):
        if b % 3 == 0:
            op, events = gen.gen_accounts_batch(16)
            body = types.accounts_to_np(events).tobytes()
        else:
            op, events = gen.gen_transfers_batch(16)
            body = types.transfers_to_np(events).tobytes()
        header, _ = cluster.execute(client, op, body)
        committed.append((op, header.timestamp, body))
    return committed


def test_view_change_after_primary_failure():
    """Kill the primary; backups elect view 1; the client retries and the
    cluster keeps serving; committed state survives."""
    cluster = Cluster(replica_count=3)
    client = cluster.add_client()
    gen = WorkloadGenerator(41)
    _commit_batches(cluster, client, gen, 4)
    committed_before = cluster.replicas[0].commit_min

    cluster.detach_replica(0)  # primary crashes
    cluster.run_ticks(60)  # silence -> SVC -> DVC -> SV
    live = cluster.replicas[1:]
    assert all(r.status == "normal" for r in live)
    assert all(r.view == 1 for r in live)
    assert live[0].is_primary  # replica 1 = view 1 % 3
    assert all(r.commit_min == committed_before for r in live)
    assert_identical_state(live)

    # client retries against the new primary (broadcast resend)
    op, events = gen.gen_accounts_batch(16)
    body = types.accounts_to_np(events).tobytes()
    client.request(op, body)
    cluster.network.run()
    if client.reply is None:
        client.resend()
        cluster.network.run()
    h, _ = client.take_reply()
    assert h.view == 1
    assert_convergence(live)
    assert_identical_state(live)


def test_view_change_preserves_uncommitted_quorum_op():
    """An op prepared by a quorum but whose commit the old primary never
    announced must survive the view change (VSR's central invariant)."""
    from tigerbeetle_tpu.vsr.header import Command, Header

    cluster = Cluster(replica_count=3)
    client = cluster.add_client()
    gen = WorkloadGenerator(43)
    _commit_batches(cluster, client, gen, 2)
    base_commit = cluster.replicas[0].commit_min

    # Block commit heartbeats and replies so backups prepare op but never
    # learn it committed; then kill the primary.
    def block(src, dst, data):
        h = Header.from_bytes(data[:128])
        if h.command == Command.commit:
            return False
        if h.command == Command.reply:
            return False
        return True

    cluster.network.filters.append(block)
    op, events = gen.gen_accounts_batch(16)
    body = types.accounts_to_np(events).tobytes()
    client.request(op, body)
    cluster.network.run()
    # primary committed locally (quorum of prepare_oks) but nobody heard
    assert cluster.replicas[0].commit_min == base_commit + 1
    assert all(r.commit_min == base_commit for r in cluster.replicas[1:])
    assert all(r.op == base_commit + 1 for r in cluster.replicas[1:])

    cluster.network.filters.clear()
    cluster.detach_replica(0)
    cluster.run_ticks(60)
    live = cluster.replicas[1:]
    assert all(r.status == "normal" for r in live)
    # The prepared op survived the view change and committed in view 1.
    assert all(r.commit_min == base_commit + 1 for r in live)
    assert_identical_state(live)

    # the client's retry is answered from the replicated client table
    # WITHOUT re-execution (the op committed exactly once)
    commit_after = live[0].commit_min
    client.resend()
    cluster.network.run()
    h1, r1 = client.take_reply()
    assert live[0].commit_min == commit_after  # answered from the table
    assert h1.op == base_commit + 1  # the surviving op's reply


def test_view_change_cascades_to_next_view():
    """If the new primary is also down, the next timeout moves to view 2."""
    cluster = Cluster(replica_count=3)
    client = cluster.add_client()
    gen = WorkloadGenerator(44)
    _commit_batches(cluster, client, gen, 2)
    committed = cluster.replicas[0].commit_min

    cluster.detach_replica(0)
    cluster.detach_replica(1)  # view-1 primary also dead
    cluster.run_ticks(200)
    # replica 2 alone cannot form a quorum: stays in view_change
    assert cluster.replicas[2].status == "view_change"
    assert cluster.replicas[2].view_candidate >= 2

    cluster.reattach_replica(1)
    cluster.run_ticks(120)
    live = cluster.replicas[1:]
    assert all(r.status == "normal" for r in live), [r.status for r in live]
    v = live[0].view
    assert v >= 2 and v % 3 != 0  # a view whose primary is alive
    assert all(r.commit_min == committed for r in live)
    assert_identical_state(live)


def test_restarted_replica_rejoins_current_view():
    """A replica restarted from disk rejoins, learns the current view via
    new-view traffic, and catches up."""
    cluster = Cluster(replica_count=3)
    client = cluster.add_client()
    gen = WorkloadGenerator(45)
    _commit_batches(cluster, client, gen, 3)

    cluster.detach_replica(0)
    cluster.run_ticks(60)
    assert cluster.replicas[1].is_primary

    # more commits in view 1 while replica 0 is down
    client.resend_view = None
    op, events = gen.gen_accounts_batch(16)
    body = types.accounts_to_np(events).tobytes()
    client.request(op, body)
    cluster.network.run()
    if client.reply is None:
        client.resend()
        cluster.network.run()
    client.take_reply()

    # restart replica 0 from its storage and let it rejoin
    r0 = cluster.restart_replica(0)
    cluster.run_ticks(60)
    assert r0.view == cluster.replicas[1].view
    assert r0.commit_min == cluster.replicas[1].commit_min
    assert_identical_state(cluster.replicas)


def test_view_change_survives_torn_slot_on_new_primary():
    """Protocol-aware recovery: the new primary's OWN copy of an acked-but-
    uncommitted op has a torn body (valid redundant header, corrupt
    prepare). The nack merge must keep the op — its header is known and no
    nack quorum exists — and repair the body from a peer (reference:
    src/vsr.zig:302-304 nacks; journal decision matrix)."""
    from tigerbeetle_tpu.io.storage import Zone
    from tigerbeetle_tpu.vsr.header import Command, Header

    cluster = Cluster(replica_count=3)
    client = cluster.add_client()
    gen = WorkloadGenerator(47)
    _commit_batches(cluster, client, gen, 2)
    base_commit = cluster.replicas[0].commit_min

    def block(src, dst, data):
        h = Header.from_bytes(data[:128])
        return h.command not in (Command.commit, Command.reply)

    cluster.network.filters.append(block)
    op, events = gen.gen_accounts_batch(16)
    client.request(op, types.accounts_to_np(events).tobytes())
    cluster.network.run()
    assert all(r.op == base_commit + 1 for r in cluster.replicas[1:])
    # remove ONLY our filter (clear() would also drop the cluster's
    # detach filter, letting the "dead" primary keep answering DVCs)
    cluster.network.filters.remove(block)

    # tear the new primary's (replica 1) prepare BODY for the acked op;
    # the redundant header ring stays intact
    r1 = cluster.replicas[1]
    torn_op = base_commit + 1
    slot = r1.journal.slot_for_op(torn_op)
    cluster.storages[1].fault(
        Zone.wal_prepares, slot * r1.journal.msg_max + 300, 128
    )
    assert r1.journal.read_prepare(torn_op) is None  # body is gone
    assert r1.journal.get_header(torn_op) is not None  # header survives

    cluster.detach_replica(0)
    cluster.run_ticks(60)
    live = cluster.replicas[1:]
    assert all(r.status == "normal" for r in live)
    # the torn op survived (header via nack merge, body repaired from
    # replica 2) and committed in the new view
    assert all(r.commit_min == base_commit + 1 for r in live)
    got = r1.journal.read_prepare(torn_op)
    assert got is not None  # body repaired into the WAL
    assert_identical_state(live)


def test_adoption_invalidates_superseded_journal_evidence():
    """A replica whose tail was truncated by adoption must destroy the
    journal evidence above the new head — otherwise the next view change's
    DVC scan (_dvc_suffix_headers reads the header mirror past self.op)
    re-advertises the superseded headers under the replica's NEW log_view,
    where best-log merging treats them as authoritative and a truncated
    prepare can shadow the op committed in the intervening view."""
    from tigerbeetle_tpu.vsr.header import Command, Header

    cluster = Cluster(replica_count=3)
    client = cluster.add_client()
    gen = WorkloadGenerator(49)
    _commit_batches(cluster, client, gen, 2)
    base = cluster.replicas[0].commit_min

    # op X = base+1 prepared ONLY by the primary (drop its prepares)
    def drop_prepares(src, dst, data):
        h = Header.from_bytes(data[:128])
        return not (h.command == Command.prepare and src == 0)

    cluster.network.filters.append(drop_prepares)
    op, events = gen.gen_accounts_batch(16)
    client.request(op, types.accounts_to_np(events).tobytes())
    cluster.network.run()
    r0 = cluster.replicas[0]
    assert r0.op == base + 1
    cluster.network.filters.remove(drop_prepares)
    client.in_flight = None

    # view change truncates X; then the old primary rejoins and adopts
    cluster.detach_replica(0)
    cluster.run_ticks(60)
    assert all(r.op == base for r in cluster.replicas[1:])
    cluster.reattach_replica(0)
    cluster.run_ticks(60)
    assert r0.status == "normal" and r0.view >= 1
    assert r0.op == base  # tail truncated by adoption

    # the superseded evidence above the head must be GONE — from the
    # mirror, and from disk (a restart rebuilds the mirror from the rings)
    assert r0.journal.get_header(base + 1) is None
    assert r0.journal.read_prepare(base + 1) is None
    suffix, head = r0._dvc_suffix_headers()
    assert head == base
    assert all(h.op <= base for h in suffix)
    r0b = cluster.restart_replica(0)
    cluster.run_ticks(60)
    assert r0b.journal.get_header(base + 1) is None or (
        r0b.op >= base + 1  # unless a NEW op legitimately took the slot
    )
    # and the cluster still commits new work
    _commit_batches(cluster, client, gen, 1)
    assert_identical_state(cluster.replicas)


def test_view_change_truncates_unreplicated_op_by_nacks():
    """An op only the dead primary ever prepared must TRUNCATE: every
    surviving replica's log head is below it (implicit nacks >= the nack
    quorum), so no possible commit is lost and the cluster moves on."""
    from tigerbeetle_tpu.vsr.header import Command, Header

    cluster = Cluster(replica_count=3)
    client = cluster.add_client()
    gen = WorkloadGenerator(48)
    _commit_batches(cluster, client, gen, 2)
    base_commit = cluster.replicas[0].commit_min

    def drop_prepares(src, dst, data):
        h = Header.from_bytes(data[:128])
        return not (h.command == Command.prepare and src == 0)

    cluster.network.filters.append(drop_prepares)
    op, events = gen.gen_accounts_batch(16)
    client.request(op, types.accounts_to_np(events).tobytes())
    cluster.network.run()
    assert cluster.replicas[0].op == base_commit + 1  # primary-only
    assert all(r.op == base_commit for r in cluster.replicas[1:])
    cluster.network.filters.remove(drop_prepares)
    # drop the client's pending request: a retransmit in the new view
    # would legitimately re-commit the same payload and mask truncation
    client.in_flight = None

    cluster.detach_replica(0)
    cluster.run_ticks(60)
    live = cluster.replicas[1:]
    assert all(r.status == "normal" for r in live)
    assert all(r.op == base_commit for r in live)  # truncated
    # the cluster is live: new work commits in the new view
    _commit_batches(cluster, client, gen, 1)
    assert all(r.commit_min == base_commit + 1 for r in live)
    assert_identical_state(live)


def test_request_start_view_with_torn_suffix_body():
    """A normal-status primary serving request_start_view with a TORN
    prepare body in its suffix (media fault after ack) must serve the SV
    from the redundant-header mirror and repair the body from a backup —
    not crash on an assert (the fault class protocol-aware recovery is
    built to tolerate)."""
    from tigerbeetle_tpu.io.storage import Zone
    from tigerbeetle_tpu.vsr.header import Command, Header

    cluster = Cluster(replica_count=3)
    client = cluster.add_client()
    gen = WorkloadGenerator(57)
    _commit_batches(cluster, client, gen, 2)
    r0 = cluster.replicas[0]
    base = r0.commit_min

    # hold prepare_oks so the next op stays in (commit_min, op]
    held = []

    def hold_oks(src, dst, data):
        h = Header.from_bytes(data[:128])
        if h.command == Command.prepare_ok:
            held.append((src, dst, data))
            return False
        return True

    cluster.network.filters.append(hold_oks)
    op, events = gen.gen_accounts_batch(16)
    client.request(op, types.accounts_to_np(events).tobytes())
    cluster.network.run()
    assert r0.op == base + 1 and r0.commit_min == base

    # tear the primary's prepare BODY; the redundant header survives
    slot = r0.journal.slot_for_op(base + 1)
    cluster.storages[0].fault(
        Zone.wal_prepares, slot * r0.journal.msg_max + 300, 128
    )
    assert r0.journal.read_prepare(base + 1) is None
    assert r0.journal.get_header(base + 1) is not None

    # a backup asks for the current start_view: must not crash, must
    # carry the torn op's REAL header (from the mirror)
    svs = []

    def sniff(src, dst, data):
        h = Header.from_bytes(data[:128])
        if h.command == Command.start_view and src == 0:
            svs.append((h, data[128 : h.size]))
        return True

    cluster.network.filters.append(sniff)
    rsv = Header(command=int(Command.request_start_view), view=0)
    rsv.set_checksum_body(b"")
    rsv.replica = 2
    rsv.set_checksum()
    cluster.network.send(2, 0, rsv.to_bytes())
    cluster.network.run()
    assert svs, "primary did not serve the SV"
    suffix_ops = {
        Header.from_bytes(body[i : i + 128]).op
        for _h, body in svs[:1]
        for i in range(0, len(body), 128)
    }
    assert base + 1 in suffix_ops
    # ...and the primary repaired the torn body from a backup
    assert r0.journal.read_prepare(base + 1) is not None

    # release the held acks: the op commits normally
    cluster.network.filters.remove(hold_oks)
    cluster.network.filters.remove(sniff)
    for src, dst, data in held:
        cluster.network.send(src, dst, data)
    cluster.network.run()
    assert r0.commit_min == base + 1
    assert_identical_state(cluster.replicas)
