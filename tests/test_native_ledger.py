"""Native C++ ledger engine (native/ledger.cc) parity + lifecycle.

The durable server's commit backend must match the Python oracle (itself
pinned to the reference's own test tables, tests/test_golden.py) code for
code and row for row — randomized differential runs over the full workload
space (two-phase, linked chains, balancing, duplicates, invalid events),
plus snapshot/restore and the Replica integration seam.
"""

import numpy as np
import pytest

from tigerbeetle_tpu import types
from tigerbeetle_tpu.models.native_ledger import NativeLedger
from tigerbeetle_tpu.models.oracle import OracleStateMachine
from tigerbeetle_tpu.testing.workload import WorkloadGenerator
from tigerbeetle_tpu.types import Operation


def _run_differential(seed: int, n_batches: int = 12, batch: int = 64):
    gen = WorkloadGenerator(seed)
    oracle = OracleStateMachine()
    nat = NativeLedger(12, 14)
    ids_seen: list[int] = []
    for b in range(n_batches):
        if b % 3 == 0:
            op, events = gen.gen_accounts_batch(batch)
        else:
            op, events = gen.gen_transfers_batch(batch)
            ids_seen.extend(t.id for t in events)
        oracle.prepare(op, len(events))
        nat.prepare(op, len(events))
        assert nat.prepare_timestamp == oracle.prepare_timestamp
        ts = oracle.prepare_timestamp
        dense_o = oracle.execute_dense(op, ts, list(events))
        dense_n = nat.execute_dense(op, ts, list(events))
        assert dense_n == dense_o, (
            f"seed {seed} batch {b}: first diff at "
            f"{next(i for i in range(len(dense_o)) if dense_o[i] != dense_n[i])}"
        )
    return oracle, nat, ids_seen


@pytest.mark.parametrize("seed", [1, 7, 23, 91])
def test_native_matches_oracle_random_workload(seed):
    oracle, nat, ids_seen = _run_differential(seed)
    # full state parity: every account and transfer row, via lookups
    acct_ids = sorted(oracle.accounts)
    assert nat.lookup_accounts(acct_ids) == oracle.lookup_accounts(acct_ids)
    probe = sorted(set(ids_seen))[:512]
    assert nat.lookup_transfers(probe) == oracle.lookup_transfers(probe)
    got = nat.counts()
    assert got["accounts"] == len(oracle.accounts)
    assert got["transfers"] == len(oracle.transfers)
    assert got["posted"] == len(oracle.posted)
    assert got["commit_timestamp"] == oracle.commit_timestamp


def test_native_snapshot_restore_roundtrip():
    oracle, nat, ids_seen = _run_differential(5, n_batches=9)
    snap = nat.snapshot_bytes()
    nat2 = NativeLedger(4, 4)  # restore grows tables as needed
    nat2.restore_bytes(snap)
    nat2.prepare_timestamp = nat.prepare_timestamp
    acct_ids = sorted(oracle.accounts)
    assert nat2.lookup_accounts(acct_ids) == oracle.lookup_accounts(acct_ids)
    assert nat2.counts() == nat.counts()

    # both continue identically after restore
    gen = WorkloadGenerator(99)
    op, events = gen.gen_transfers_batch(48)
    for led in (nat, nat2):
        led.prepare(op, len(events))
    ts = nat.prepare_timestamp
    assert nat.execute_dense(op, ts, list(events)) == nat2.execute_dense(
        op, ts, list(events)
    )
    assert nat.snapshot_bytes() == nat2.snapshot_bytes()


def test_native_two_phase_and_chains_explicit():
    """Deterministic two-phase + chain scenario (not seed-dependent)."""
    oracle = OracleStateMachine()
    nat = NativeLedger(8, 10)
    A = [types.Account(id=i, ledger=1, code=1) for i in (1, 2, 3)]
    for led in (oracle, nat):
        led.prepare(Operation.create_accounts, 3)
    ts = oracle.prepare_timestamp
    assert oracle.execute_dense(Operation.create_accounts, ts, list(A)) == \
        nat.execute_dense(Operation.create_accounts, ts, list(A)) == [0, 0, 0]

    F = types.TransferFlags
    T = [
        types.Transfer(id=10, debit_account_id=1, credit_account_id=2,
                       amount=100, ledger=1, code=1, flags=int(F.pending),
                       timeout=60),
        # linked chain that breaks (same-account transfer is invalid)
        types.Transfer(id=11, debit_account_id=1, credit_account_id=3,
                       amount=5, ledger=1, code=1, flags=int(F.linked)),
        types.Transfer(id=12, debit_account_id=2, credit_account_id=2,
                       amount=5, ledger=1, code=1),
        # standalone ok
        types.Transfer(id=13, debit_account_id=3, credit_account_id=1,
                       amount=7, ledger=1, code=1),
    ]
    for led in (oracle, nat):
        led.prepare(Operation.create_transfers, len(T))
    ts = oracle.prepare_timestamp
    d_o = oracle.execute_dense(Operation.create_transfers, ts, list(T))
    d_n = nat.execute_dense(Operation.create_transfers, ts, list(T))
    assert d_n == d_o
    assert d_o[1] == 1 and d_o[2] != 0 and d_o[3] == 0  # chain broke

    # post the pending, then double-post (already_posted), then void
    P = [types.Transfer(id=20, pending_id=10, ledger=1, code=1,
                        flags=int(F.post_pending_transfer))]
    for led in (oracle, nat):
        led.prepare(Operation.create_transfers, 1)
    ts = oracle.prepare_timestamp
    assert oracle.execute_dense(Operation.create_transfers, ts, list(P)) == \
        nat.execute_dense(Operation.create_transfers, ts, list(P)) == [0]
    P2 = [types.Transfer(id=21, pending_id=10, ledger=1, code=1,
                         flags=int(F.void_pending_transfer))]
    for led in (oracle, nat):
        led.prepare(Operation.create_transfers, 1)
    ts = oracle.prepare_timestamp
    d_o = oracle.execute_dense(Operation.create_transfers, ts, list(P2))
    d_n = nat.execute_dense(Operation.create_transfers, ts, list(P2))
    assert d_n == d_o  # pending_transfer_already_posted
    ids = [1, 2, 3]
    assert nat.lookup_accounts(ids) == oracle.lookup_accounts(ids)


def test_native_reply_encoding_matches_state_machine():
    """drain_reply's vectorized sparse encoding == the wire format."""
    from tigerbeetle_tpu.state_machine import StateMachine, decode_results

    nat = NativeLedger(8, 10)
    sm = StateMachine(nat)
    acc = types.accounts_to_np([
        types.Account(id=1, ledger=1, code=1),
        types.Account(id=0, ledger=1, code=1),  # id_must_not_be_zero
        types.Account(id=2, ledger=0, code=1),  # ledger_must_not_be_zero
    ]).tobytes()
    sm.prepare(Operation.create_accounts, acc)
    reply = sm.commit_finish(
        sm.commit_async(Operation.create_accounts, sm.prepare_timestamp, acc)
    )
    assert decode_results(reply, Operation.create_accounts) == [(1, 6), (2, 13)]


def test_native_throughput_sanity():
    """Sanity floor, not a benchmark: the engine must stay orders of
    magnitude above the Python oracle (~50k TPS). The threshold is set
    far below the measured ~2.8M TPS so loaded/slow CI hosts stay green;
    bench.py reports the real number."""
    import time

    nat = NativeLedger(16, 22)
    n_acc = 10_000
    arr = np.zeros(n_acc, dtype=types.ACCOUNT_DTYPE)
    arr["id_lo"] = np.arange(1, n_acc + 1)
    arr["ledger"] = 1
    arr["code"] = 1
    nat.prepare(Operation.create_accounts, n_acc)
    assert not any(nat.execute_dense(
        Operation.create_accounts, nat.prepare_timestamp, arr
    ))
    rng = np.random.default_rng(1)
    batches = []
    for g in range(12):
        t = np.zeros(8190, dtype=types.TRANSFER_DTYPE)
        t["id_lo"] = np.arange(1_000_000 + g * 8190, 1_000_000 + (g + 1) * 8190)
        dr = rng.integers(1, n_acc + 1, size=8190, dtype=np.uint64)
        off = rng.integers(1, n_acc, size=8190, dtype=np.uint64)
        t["debit_account_id_lo"] = dr
        t["credit_account_id_lo"] = (dr - 1 + off) % n_acc + 1
        t["amount_lo"] = 1
        t["ledger"] = 1
        t["code"] = 1
        batches.append(t)
    t0 = time.perf_counter()
    last = None
    for b in batches:
        nat.prepare(Operation.create_transfers, len(b))
        last = nat.execute_async(
            Operation.create_transfers, nat.prepare_timestamp, b
        )
    last.wait()  # engine worker FIFO: the last done => all done
    assert last.failures == 0
    dt = time.perf_counter() - t0
    tps = 12 * 8190 / dt
    assert tps > 250_000, f"native engine too slow: {tps:,.0f} TPS"
