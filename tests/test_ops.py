"""Unit tests for the u128 limb arithmetic and the HBM hash table ops."""

import random

import jax.numpy as jnp
import numpy as np

from tigerbeetle_tpu.ops import hashtable as ht
from tigerbeetle_tpu.ops import u128

U128_MAX = (1 << 128) - 1
U64_MAX = (1 << 64) - 1


def _split_np(xs):
    lo = np.array([x & U64_MAX for x in xs], dtype=np.uint64)
    hi = np.array([x >> 64 for x in xs], dtype=np.uint64)
    return jnp.asarray(lo), jnp.asarray(hi)


def _join_np(lo, hi):
    return [(int(h) << 64) | int(l) for l, h in zip(np.asarray(lo), np.asarray(hi))]


def test_u128_add_sub_cmp_random():
    rng = random.Random(7)
    edge = [0, 1, U64_MAX, U64_MAX + 1, U128_MAX - 1, U128_MAX]
    xs = edge + [rng.randint(0, U128_MAX) for _ in range(200)]
    ys = list(reversed(edge)) + [rng.randint(0, U128_MAX) for _ in range(200)]
    a_lo, a_hi = _split_np(xs)
    b_lo, b_hi = _split_np(ys)

    lo, hi, c = u128.add(a_lo, a_hi, b_lo, b_hi)
    assert _join_np(lo, hi) == [(a + b) & U128_MAX for a, b in zip(xs, ys)]
    assert np.asarray(c).tolist() == [a + b > U128_MAX for a, b in zip(xs, ys)]

    lo, hi, brw = u128.sub(a_lo, a_hi, b_lo, b_hi)
    assert _join_np(lo, hi) == [(a - b) & U128_MAX for a, b in zip(xs, ys)]
    assert np.asarray(brw).tolist() == [a < b for a, b in zip(xs, ys)]

    lo, hi = u128.sat_sub(a_lo, a_hi, b_lo, b_hi)
    assert _join_np(lo, hi) == [max(0, a - b) for a, b in zip(xs, ys)]

    assert np.asarray(u128.lt(a_lo, a_hi, b_lo, b_hi)).tolist() == [
        a < b for a, b in zip(xs, ys)
    ]
    assert np.asarray(u128.gt(a_lo, a_hi, b_lo, b_hi)).tolist() == [
        a > b for a, b in zip(xs, ys)
    ]
    assert np.asarray(u128.eq(a_lo, a_hi, b_lo, b_hi)).tolist() == [
        a == b for a, b in zip(xs, ys)
    ]
    lo, hi = u128.min_(a_lo, a_hi, b_lo, b_hi)
    assert _join_np(lo, hi) == [min(a, b) for a, b in zip(xs, ys)]
    assert np.asarray(u128.sum_overflows(a_lo, a_hi, b_lo, b_hi)).tolist() == [
        a + b > U128_MAX for a, b in zip(xs, ys)
    ]
    assert np.asarray(u128.is_zero(a_lo, a_hi)).tolist() == [a == 0 for a in xs]
    assert np.asarray(u128.is_max(a_lo, a_hi)).tolist() == [a == U128_MAX for a in xs]


def test_u64_sum_overflows():
    a = jnp.asarray(np.array([U64_MAX, U64_MAX - 1, 0], dtype=np.uint64))
    b = jnp.asarray(np.array([1, 1, 0], dtype=np.uint64))
    assert np.asarray(u128.sum_overflows_u64(a, b)).tolist() == [True, False, False]


def _key4(x):
    return jnp.asarray(
        np.array(
            [[x & 0xFFFFFFFF, (x >> 32) & 0xFFFFFFFF,
              (x >> 64) & 0xFFFFFFFF, (x >> 96) & 0xFFFFFFFF]],
            dtype=np.uint32,
        )
    )[0]


def _key4_batch(keys):
    out = np.zeros((len(keys), 4), dtype=np.uint32)
    for i, x in enumerate(keys):
        out[i] = (x & 0xFFFFFFFF, (x >> 32) & 0xFFFFFFFF,
                  (x >> 64) & 0xFFFFFFFF, (x >> 96) & 0xFFFFFFFF)
    return jnp.asarray(out)


def _rows_from_key4(key4):
    B = key4.shape[0]
    rows = jnp.zeros((B, 32), dtype=jnp.uint32)
    return rows.at[:, :4].set(key4)


def _mk_table(log2):
    return jnp.zeros(((1 << log2) + 1, 32), dtype=jnp.uint32)


def test_hashtable_insert_then_lookup():
    log2 = 8
    rows = _mk_table(log2)
    claim = jnp.full((1 << log2) + 1, ht.CLAIM_FREE, dtype=jnp.uint32)
    rng = random.Random(3)
    keys = sorted({rng.randint(1, U128_MAX - 1) for _ in range(150)})
    k4 = _key4_batch(keys)
    ins = _rows_from_key4(k4)
    active = jnp.ones(len(keys), dtype=bool)
    slots, rows, claim, resolved = ht.insert_rows(ins, active, rows, claim, log2)
    assert bool(jnp.all(resolved))
    slots = np.asarray(slots)
    # All inserted at distinct, in-range slots; claim scratch fully reset.
    assert len(set(slots.tolist())) == len(keys)
    assert slots.max() < (1 << log2)
    assert bool(jnp.all(claim == ht.CLAIM_FREE))
    # Every key found at its claimed slot.
    got_slots, found, res = ht.lookup(k4, rows, log2)
    assert bool(jnp.all(found)) and bool(jnp.all(res))
    assert np.array_equal(np.asarray(got_slots), slots)
    # Absent keys (hi limb flipped) not found.
    absent = k4.at[:, 3].set(k4[:, 3] ^ jnp.uint32(0xDEADBEEF))
    _, found2, _ = ht.lookup(absent, rows, log2)
    assert not bool(jnp.any(found2))


def test_hashtable_insert_inactive_lanes_untouched():
    log2 = 6
    rows = _mk_table(log2)
    claim = jnp.full((1 << log2) + 1, ht.CLAIM_FREE, dtype=jnp.uint32)
    k4 = _key4_batch([10, 11, 12, 13])
    active = jnp.asarray([True, False, True, False])
    slots, rows, claim, _ = ht.insert_rows(_rows_from_key4(k4), active, rows, claim, log2)
    _, found, _ = ht.lookup(k4, rows, log2)
    assert np.asarray(found).tolist() == [True, False, True, False]
    assert int(np.asarray(slots)[1]) == 1 << log2  # dump slot for inactive


def test_hashtable_scalar_probe_and_tombstone():
    log2 = 4
    rows = _mk_table(log2)
    k4 = _key4(42)
    slot, free_ok = ht.probe_free(k4, rows, log2)
    assert bool(free_ok)
    rows = rows.at[slot, :4].set(k4)
    s2, found, _ = ht.lookup(k4, rows, log2)
    assert bool(found) and int(s2) == int(slot)
    # Tombstone the slot: lookup misses, probe_free reuses it.
    rows = rows.at[slot].set(jnp.full(32, 0xFFFFFFFF, dtype=jnp.uint32))
    _, found3, _ = ht.lookup(k4, rows, log2)
    assert not bool(found3)
    s4, _ = ht.probe_free(k4, rows, log2)
    assert int(s4) == int(slot)


def test_hashtable_lookup_skips_tombstone_in_chain():
    # A key whose probe start is tombstoned must still be found further down
    # its chain (tombstone != empty for probe termination).
    log2 = 4
    rows = _mk_table(log2)
    k4 = _key4(777)
    h = int(ht.hash_key4(k4, log2))
    nxt = (h + int(ht.probe_step(k4, log2))) & ((1 << log2) - 1)
    rows = rows.at[h].set(jnp.full(32, 0xFFFFFFFF, dtype=jnp.uint32))
    rows = rows.at[nxt, :4].set(k4)
    s, found, _ = ht.lookup(k4, rows, log2)
    assert bool(found) and int(s) == nxt
