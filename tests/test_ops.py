"""Unit tests for the u128 limb arithmetic and the HBM hash table ops."""

import random

import jax.numpy as jnp
import numpy as np

from tigerbeetle_tpu.ops import hashtable as ht
from tigerbeetle_tpu.ops import u128

U128_MAX = (1 << 128) - 1
U64_MAX = (1 << 64) - 1


def _split_np(xs):
    lo = np.array([x & U64_MAX for x in xs], dtype=np.uint64)
    hi = np.array([x >> 64 for x in xs], dtype=np.uint64)
    return jnp.asarray(lo), jnp.asarray(hi)


def _join_np(lo, hi):
    return [(int(h) << 64) | int(l) for l, h in zip(np.asarray(lo), np.asarray(hi))]


def test_u128_add_sub_cmp_random():
    rng = random.Random(7)
    edge = [0, 1, U64_MAX, U64_MAX + 1, U128_MAX - 1, U128_MAX]
    xs = edge + [rng.randint(0, U128_MAX) for _ in range(200)]
    ys = list(reversed(edge)) + [rng.randint(0, U128_MAX) for _ in range(200)]
    a_lo, a_hi = _split_np(xs)
    b_lo, b_hi = _split_np(ys)

    lo, hi, c = u128.add(a_lo, a_hi, b_lo, b_hi)
    assert _join_np(lo, hi) == [(a + b) & U128_MAX for a, b in zip(xs, ys)]
    assert np.asarray(c).tolist() == [a + b > U128_MAX for a, b in zip(xs, ys)]

    lo, hi, brw = u128.sub(a_lo, a_hi, b_lo, b_hi)
    assert _join_np(lo, hi) == [(a - b) & U128_MAX for a, b in zip(xs, ys)]
    assert np.asarray(brw).tolist() == [a < b for a, b in zip(xs, ys)]

    lo, hi = u128.sat_sub(a_lo, a_hi, b_lo, b_hi)
    assert _join_np(lo, hi) == [max(0, a - b) for a, b in zip(xs, ys)]

    assert np.asarray(u128.lt(a_lo, a_hi, b_lo, b_hi)).tolist() == [
        a < b for a, b in zip(xs, ys)
    ]
    assert np.asarray(u128.gt(a_lo, a_hi, b_lo, b_hi)).tolist() == [
        a > b for a, b in zip(xs, ys)
    ]
    assert np.asarray(u128.eq(a_lo, a_hi, b_lo, b_hi)).tolist() == [
        a == b for a, b in zip(xs, ys)
    ]
    lo, hi = u128.min_(a_lo, a_hi, b_lo, b_hi)
    assert _join_np(lo, hi) == [min(a, b) for a, b in zip(xs, ys)]
    assert np.asarray(u128.sum_overflows(a_lo, a_hi, b_lo, b_hi)).tolist() == [
        a + b > U128_MAX for a, b in zip(xs, ys)
    ]
    assert np.asarray(u128.is_zero(a_lo, a_hi)).tolist() == [a == 0 for a in xs]
    assert np.asarray(u128.is_max(a_lo, a_hi)).tolist() == [a == U128_MAX for a in xs]


def test_u64_sum_overflows():
    a = jnp.asarray(np.array([U64_MAX, U64_MAX - 1, 0], dtype=np.uint64))
    b = jnp.asarray(np.array([1, 1, 0], dtype=np.uint64))
    assert np.asarray(u128.sum_overflows_u64(a, b)).tolist() == [True, False, False]


def _mk_table(log2):
    rows = (1 << log2) + 1
    return jnp.zeros(rows, dtype=jnp.uint64), jnp.zeros(rows, dtype=jnp.uint64)


def test_hashtable_insert_then_lookup():
    log2 = 8
    k_lo, k_hi = _mk_table(log2)
    claim = jnp.full((1 << log2) + 1, ht.CLAIM_FREE, dtype=jnp.uint32)
    rng = random.Random(3)
    keys = sorted({rng.randint(1, U128_MAX - 1) for _ in range(150)})
    lo, hi = _split_np(keys)
    active = jnp.ones(len(keys), dtype=bool)
    slots, k_lo, k_hi, claim = ht.insert_slots(lo, hi, active, k_lo, k_hi, claim, log2)
    slots = np.asarray(slots)
    # All inserted at distinct, in-range slots; claim scratch fully reset.
    assert len(set(slots.tolist())) == len(keys)
    assert slots.max() < (1 << log2)
    assert bool(jnp.all(claim == ht.CLAIM_FREE))
    # Every key found at its claimed slot.
    got_slots, found = ht.lookup(lo, hi, k_lo, k_hi, log2)
    assert bool(jnp.all(found))
    assert np.array_equal(np.asarray(got_slots), slots)
    # Absent keys (same lo limb, different hi limb) not found.
    absent_hi = hi ^ jnp.uint64(0xDEADBEEF)
    _, found2 = ht.lookup(lo, absent_hi, k_lo, k_hi, log2)
    assert not bool(jnp.any(found2))


def test_hashtable_insert_inactive_lanes_untouched():
    log2 = 6
    k_lo, k_hi = _mk_table(log2)
    claim = jnp.full((1 << log2) + 1, ht.CLAIM_FREE, dtype=jnp.uint32)
    lo, hi = _split_np([10, 11, 12, 13])
    active = jnp.asarray([True, False, True, False])
    slots, k_lo, k_hi, claim = ht.insert_slots(lo, hi, active, k_lo, k_hi, claim, log2)
    _, found = ht.lookup(lo, hi, k_lo, k_hi, log2)
    assert np.asarray(found).tolist() == [True, False, True, False]
    assert int(np.asarray(slots)[1]) == 1 << log2  # dump slot for inactive


def test_hashtable_scalar_probe_and_tombstone():
    log2 = 4
    k_lo, k_hi = _mk_table(log2)
    slot = ht.probe_free_scalar(jnp.uint64(42), jnp.uint64(0), k_lo, k_hi, log2)
    k_lo = k_lo.at[slot].set(jnp.uint64(42))
    s2, found = ht.lookup(jnp.uint64(42), jnp.uint64(0), k_lo, k_hi, log2)
    assert bool(found) and int(s2) == int(slot)
    # Tombstone the slot: lookup misses, probe_free reuses it.
    k_lo = k_lo.at[slot].set(ht.TOMB)
    k_hi = k_hi.at[slot].set(ht.TOMB)
    _, found3 = ht.lookup(jnp.uint64(42), jnp.uint64(0), k_lo, k_hi, log2)
    assert not bool(found3)
    s4 = ht.probe_free_scalar(jnp.uint64(42), jnp.uint64(0), k_lo, k_hi, log2)
    assert int(s4) == int(slot)


def test_hashtable_lookup_skips_tombstone_in_chain():
    # Two keys on one collision chain: tombstoning the first must not hide
    # the second (tombstone != empty for probe termination).
    log2 = 4
    k_lo, k_hi = _mk_table(log2)
    h0 = int(ht.hash_u128(jnp.uint64(1), jnp.uint64(0), log2))
    k_lo = k_lo.at[h0].set(jnp.uint64(1))
    nxt = (h0 + 1) & ((1 << log2) - 1)
    k_lo = k_lo.at[nxt].set(jnp.uint64(777))
    s, found = ht.lookup(jnp.uint64(777), jnp.uint64(0), k_lo, k_hi, log2)
    # 777 may hash elsewhere; place it explicitly on 1's chain instead.
    k_lo = k_lo.at[nxt].set(jnp.uint64(0))
    h777 = int(ht.hash_u128(jnp.uint64(777), jnp.uint64(0), log2))
    if h777 != h0:
        # Force a chain: fill h777..h0 path is fiddly; instead just verify
        # tombstone-skip on 777's own chain.
        k_lo = k_lo.at[h777].set(ht.TOMB)
        k_hi = k_hi.at[h777].set(ht.TOMB)
        nxt777 = (h777 + 1) & ((1 << log2) - 1)
        k_lo = k_lo.at[nxt777].set(jnp.uint64(777))
        k_hi = k_hi.at[nxt777].set(jnp.uint64(0))
        s, found = ht.lookup(jnp.uint64(777), jnp.uint64(0), k_lo, k_hi, log2)
        assert bool(found) and int(s) == nxt777
    else:
        k_lo = k_lo.at[h0].set(ht.TOMB)
        k_hi = k_hi.at[h0].set(ht.TOMB)
        k_lo = k_lo.at[nxt].set(jnp.uint64(777))
        s, found = ht.lookup(jnp.uint64(777), jnp.uint64(0), k_lo, k_hi, log2)
        assert bool(found) and int(s) == nxt
