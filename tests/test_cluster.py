"""Deterministic 3-replica cluster: VSR normal path over the seams
(VERDICT round-1 item 6). Real replicas, real wire bytes, fake
storage/network/time; StateChecker asserts one linear history and
bit-exact cross-replica state."""

import numpy as np
import pytest

from tigerbeetle_tpu import types
from tigerbeetle_tpu.state_machine import encode_ids
from tigerbeetle_tpu.testing.cluster import Cluster
from tigerbeetle_tpu.testing.state_checker import (
    assert_convergence,
    assert_identical_state,
    assert_matches_oracle,
)
from tigerbeetle_tpu.testing.workload import WorkloadGenerator
from tigerbeetle_tpu.types import Operation


def _batch_bodies(gen, n_batches, batch_size=24):
    out = []
    for b in range(n_batches):
        if b % 3 == 0:
            op, events = gen.gen_accounts_batch(batch_size)
            out.append((op, types.accounts_to_np(events).tobytes()))
        else:
            op, events = gen.gen_transfers_batch(batch_size)
            out.append((op, types.transfers_to_np(events).tobytes()))
    return out


@pytest.fixture(scope="module")
def loaded_cluster():
    cluster = Cluster(replica_count=3)
    client = cluster.add_client()
    committed = []
    for op, body in _batch_bodies(WorkloadGenerator(21), 7):
        header, _reply = cluster.execute(client, op, body)
        committed.append((op, header.timestamp, body))
    return cluster, client, committed


def test_cluster_commits_and_converges(loaded_cluster):
    cluster, _client, committed = loaded_cluster
    assert_convergence(cluster.replicas)
    assert_identical_state(cluster.replicas)
    assert cluster.replicas[0].commit_min == len(committed) + 1  # + register
    assert_matches_oracle(cluster.replicas[0], committed)


def test_cluster_replies_match_oracle(loaded_cluster):
    """The primary's wire replies equal an oracle replay's replies."""
    cluster, _client, committed = loaded_cluster
    from tigerbeetle_tpu.models.oracle import OracleStateMachine
    from tigerbeetle_tpu.state_machine import StateMachine

    sm = StateMachine(OracleStateMachine(), cluster.cluster_config)
    client2 = cluster.add_client()
    for op, ts, body in committed:
        expect = sm.commit(op, ts, body)
        if op == Operation.create_transfers:
            # re-submitting through the cluster would duplicate state; only
            # compare replies for the original run via lookups below
            pass
    # lookups through consensus: same rows as the oracle
    oracle = sm.backend
    ids = list(oracle.accounts.keys())[:16]
    header, reply = cluster.execute(
        client2, Operation.lookup_accounts, encode_ids(ids)
    )
    rows = np.frombuffer(reply, dtype=types.ACCOUNT_DTYPE)
    assert [types.Account.from_np(r) for r in rows] == oracle.lookup_accounts(ids)


def test_cluster_duplicate_request_replied_from_table(loaded_cluster):
    """Resending the in-flight request returns the SAME reply bytes without
    re-execution (replicated client table idempotency)."""
    cluster, client, _ = loaded_cluster
    accounts = [types.Account(id=999_000_001, ledger=1, code=1)]
    body = types.accounts_to_np(accounts).tobytes()
    client.request(Operation.create_accounts, body)
    cluster.network.run()
    h1, r1 = client.take_reply()
    commit_before = cluster.replicas[0].commit_min

    client.request_number -= 1  # simulate a lost-reply retry of the same id
    client.in_flight = None
    client.request(Operation.create_accounts, body)
    cluster.network.run()
    h2, r2 = client.take_reply()
    assert (h2.checksum, r2) == (h1.checksum, r1)
    assert cluster.replicas[0].commit_min == commit_before  # not re-executed


def test_cluster_backup_restart_recovers(loaded_cluster):
    cluster, client, committed = loaded_cluster
    r2 = cluster.restart_replica(2)
    assert r2.commit_min == cluster.replicas[0].commit_min
    assert_identical_state(cluster.replicas)

    # and the cluster keeps serving afterwards
    op, body = _batch_bodies(WorkloadGenerator(5), 1)[0]
    cluster.execute(client, op, body)
    assert_convergence(cluster.replicas)
    assert_identical_state(cluster.replicas)


def test_cluster_unregistered_client_evicted():
    cluster = Cluster(replica_count=3)
    client = cluster.add_client()
    client.session = 4242  # wrong session
    accounts = [types.Account(id=1, ledger=1, code=1)]
    client.request(Operation.create_accounts, types.accounts_to_np(accounts).tobytes())
    cluster.network.run()
    assert client.evicted


def test_cluster_retransmit_while_in_pipeline_not_duplicated():
    """A request retransmitted while its prepare awaits quorum must NOT be
    prepared (and executed) a second time."""
    cluster = Cluster(replica_count=3)
    client = cluster.add_client()

    # Hold all prepare_oks so the op sits in the pipeline.
    from tigerbeetle_tpu.vsr.header import Command, Header

    held = []

    def hold_oks(src, dst, data):
        h = Header.from_bytes(data[:128])
        if h.command == Command.prepare_ok:
            held.append((src, dst, data))
            return False
        return True

    cluster.network.filters.append(hold_oks)
    body = types.accounts_to_np([types.Account(id=7, ledger=1, code=1)]).tobytes()
    client.request(Operation.create_accounts, body)
    cluster.network.run()
    assert cluster.replicas[0].commit_min == 1  # register only; op 2 pending
    assert len(cluster.replicas[0].pipeline) == 1

    client.resend()  # timeout retry of the same request
    cluster.network.run()
    assert len(cluster.replicas[0].pipeline) == 1  # NOT prepared twice
    assert cluster.replicas[0].op == 2

    # release the held acks: commits exactly once
    cluster.network.filters.clear()
    for src, dst, data in held:
        cluster.network.send(src, dst, data)
    cluster.network.run()
    h, r = client.take_reply()
    assert r == b""  # ok — a re-execution would return exists (21)
    assert cluster.replicas[0].commit_min == 2
    assert_identical_state(cluster.replicas)


def test_cluster_checkpoint_on_wal_wrap_and_restart():
    """More ops than checkpoint_interval: replicas checkpoint instead of
    letting the WAL ring wrap over un-checkpointed ops; a restart then
    recovers from snapshot + tail."""
    cluster = Cluster(replica_count=3)
    client = cluster.add_client()
    interval = cluster.cluster_config.checkpoint_interval  # 60 in TEST_CLUSTER
    gen = WorkloadGenerator(9)
    committed = []
    for op, body in _batch_bodies(gen, interval + 6, batch_size=4):
        header, _ = cluster.execute(client, op, body)
        committed.append((op, header.timestamp, body))
    assert cluster.replicas[0].checkpoint_op > 0  # a checkpoint happened
    r1 = cluster.restart_replica(1)
    assert r1.commit_min == cluster.replicas[0].commit_min
    assert_identical_state(cluster.replicas)
    assert_matches_oracle(cluster.replicas[1], committed)


def test_cluster_pipelined_requests_from_many_clients():
    """Multiple clients' requests pipeline through the primary and commit
    in op order."""
    cluster = Cluster(replica_count=3)
    clients = [cluster.add_client() for _ in range(4)]
    gen = WorkloadGenerator(31)
    bodies = _batch_bodies(gen, 4)
    # dispatch all four without pumping, then pump once
    for c, (op, body) in zip(clients, bodies):
        c.request(op, body)
    cluster.network.run()
    for c in clients:
        c.take_reply()
    assert_convergence(cluster.replicas)
    assert_identical_state(cluster.replicas)


def test_reply_persisted_across_restart():
    """A duplicate request arriving AFTER a checkpoint + restart must be
    answered with the ORIGINAL reply bytes from the client_replies zone
    (reference: src/vsr/client_replies.zig) — the checkpoint meta strips
    reply bytes, and ops at/below the checkpoint are not replayed."""
    cluster = Cluster(replica_count=3)
    client = cluster.add_client()
    body = types.accounts_to_np(
        [types.Account(id=71, ledger=1, code=1)]
    ).tobytes()
    h, _ = cluster.execute(client, Operation.create_accounts, body)
    # checkpoint so the request's op is NOT in the replayed WAL tail
    for r in cluster.replicas:
        r.checkpoint()
    commit = cluster.replicas[0].commit_min

    # full-cluster restart: every replica's reply bytes can only come
    # from its client_replies zone
    for i in range(3):
        cluster.restart_replica(i)
    cluster.run_ticks(80)
    normal = [r for r in cluster.replicas if r.status == "normal"]
    assert normal, [r.status for r in cluster.replicas]
    for r in normal:
        assert r.client_table[client.client_id]["reply"] is not None, (
            r.replica, "reply not restored from the client_replies zone"
        )
    primary = next(r for r in normal if r.view % 3 == r.replica)

    # simulate a late retransmit of the original request
    from tigerbeetle_tpu.vsr.header import Command, Header

    rq = Header(
        command=int(Command.request),
        operation=int(Operation.create_accounts),
        client=client.client_id,
        context=client.session,
        request=1,
    )
    rq.set_checksum_body(body)
    rq.set_checksum()
    seen = []

    def sniff(src, dst, data):
        h2 = Header.from_bytes(data[:128])
        if dst == client.client_id and h2.command == Command.reply:
            seen.append(h2)
        return True

    cluster.network.filters.append(sniff)
    cluster.network.send(client.client_id, primary.replica,
                         rq.to_bytes() + body)
    cluster.network.run()
    cluster.network.filters.remove(sniff)
    assert seen, "no reply to the retransmit"
    assert seen[0].checksum == h.checksum  # bit-identical original reply
    assert primary.commit_min == commit  # not re-executed


def test_commit_window_overlaps_journal_and_device():
    """Commit-stage overlap (reference: src/vsr/replica.zig:52-70): with
    commit_window > 0 the primary DISPATCHES a device commit and returns —
    the next op's journal write and broadcast run while the previous
    batch's results are still on device (un-drained). Replies flow on
    flush_commits()."""
    cluster = Cluster(replica_count=1)
    r = cluster.replicas[0]
    c1 = cluster.add_client()
    c2 = cluster.add_client()
    r.commit_window = 4

    gen = WorkloadGenerator(61)
    op, events = gen.gen_accounts_batch(16)
    body1 = types.accounts_to_np(events).tobytes()
    op2, events2 = gen.gen_accounts_batch(16)
    body2 = types.accounts_to_np(events2).tobytes()

    base = r.commit_min
    c1.request(op, body1)
    c2.request(op2, body2)
    cluster.network.run()
    r.pump_commits()  # the real event loop calls this after each pump turn

    # Both ops are journaled AND dispatched (commit_min advanced) — op 2's
    # journal write happened while op 1's device batch was still in
    # flight — but neither has been drained or replied to yet.
    assert r.commit_min == base + 2
    assert len(r._inflight) == 2
    for entry in r._inflight:
        handle = entry["handle"]
        assert handle is not None and not isinstance(handle, bytes)
        assert handle[1].dense is None  # results still on device
    assert r.journal.read_prepare(base + 1) is not None
    assert r.journal.read_prepare(base + 2) is not None
    assert c1.reply is None and c2.reply is None

    # flush finalizes in op order and the replies go out
    r.flush_commits()
    cluster.network.run()
    h1, r1 = c1.take_reply()
    h2, r2 = c2.take_reply()
    assert h1.op == base + 1 and h2.op == base + 2

    # the deferred replies are also in the client table + replies zone
    for c in (c1, c2):
        e = r.client_table[c.client_id]
        assert e["reply"] is not None and e.get("slot") is not None

    # a retransmit while dispatched-but-unfinalized must not re-execute:
    # covered by the _inflight scan in _on_request (regression guard)
    c1.request(op, body1)
    cluster.network.run()
    r.pump_commits()
    commit_after_dispatch = r.commit_min
    c1.resend()  # retransmit while dispatched-but-unfinalized
    cluster.network.run()
    r.pump_commits()
    r.flush_commits()
    cluster.network.run()
    assert r.commit_min == commit_after_dispatch  # executed exactly once
    c1.take_reply()


def test_client_eviction_at_clients_max():
    """clients_max+1 sessions: the OLDEST session is evicted (not silently
    left unpersisted), the evicted client learns via the eviction command,
    and every other session still answers duplicates from the table
    (reference: src/vsr/replica.zig:3758-3860, src/vsr.zig:136)."""
    from tigerbeetle_tpu.constants import ConfigCluster

    small = ConfigCluster(
        journal_slot_count=64, lsm_batch_multiple=4, clients_max=4,
    )
    cluster = Cluster(replica_count=3, cluster=small)
    clients = [cluster.add_client() for _ in range(4)]
    primary = cluster.replicas[0]
    assert len(primary.client_table) == 4

    newcomer = cluster.add_client()  # 5th session: evicts the oldest
    assert len(primary.client_table) == 4
    assert clients[0].client_id not in primary.client_table
    assert clients[0].evicted  # the eviction command reached it
    # every replica evicted the SAME session (deterministic choice)
    for r in cluster.replicas:
        assert clients[0].client_id not in r.client_table

    # surviving + new sessions still transact, duplicates still answered
    gen = WorkloadGenerator(71)
    op, events = gen.gen_accounts_batch(8)
    body = types.accounts_to_np(events).tobytes()
    clients[1].request(op, body)
    wire = clients[1].in_flight
    cluster.network.run()
    clients[1].take_reply()
    commit = primary.commit_min
    cluster.network.send(clients[1].client_id, 0, wire)  # late duplicate
    cluster.network.run()
    assert primary.commit_min == commit  # answered from table, no re-commit
    op2, events2 = gen.gen_accounts_batch(8)
    cluster.execute(newcomer, op2, types.accounts_to_np(events2).tobytes())
    assert_identical_state(cluster.replicas)


def test_evicted_client_request_rejected():
    """A request on an evicted session gets the eviction command, not an
    execution."""
    from tigerbeetle_tpu.constants import ConfigCluster

    small = ConfigCluster(
        journal_slot_count=64, lsm_batch_multiple=4, clients_max=2,
    )
    cluster = Cluster(replica_count=3, cluster=small)
    c0 = cluster.add_client()
    cluster.add_client()
    cluster.add_client()  # evicts c0
    assert c0.evicted
    # the eviction surfaces as a typed error from the wait path; a driver
    # that insists on reusing the dead session consumes it first
    import pytest

    from tigerbeetle_tpu.vsr.client import SessionEvicted

    with pytest.raises(SessionEvicted):
        c0.poll()
    commit = cluster.replicas[0].commit_min
    gen = WorkloadGenerator(72)
    op, events = gen.gen_accounts_batch(8)
    c0.request(op, types.accounts_to_np(events).tobytes())
    cluster.network.run()
    assert cluster.replicas[0].commit_min == commit  # not executed


def test_group_commit_matches_oracle():
    """Fused group commits (several quorum-ready create_transfers prepares
    in ONE device dispatch) produce bit-identical state and replies vs the
    scalar oracle replaying the same ops one at a time."""
    from tigerbeetle_tpu.types import TRANSFER_DTYPE

    cluster = Cluster(replica_count=1)
    r = cluster.replicas[0]
    clients = [cluster.add_client() for _ in range(4)]
    r.commit_window = 8
    committed = []
    r.commit_hook = lambda h, b: committed.append(
        (Operation(h.operation), h.timestamp, b)
    )

    # accounts 1..40
    acc = np.zeros(40, dtype=types.ACCOUNT_DTYPE)
    acc["id_lo"] = np.arange(1, 41)
    acc["ledger"] = 1
    acc["code"] = 1
    clients[0].request(Operation.create_accounts, acc.tobytes())
    cluster.network.run()
    r.pump_commits()
    r.flush_commits()
    cluster.network.run()
    clients[0].take_reply()

    # four fast-tier transfer batches arriving in ONE pump turn -> one
    # fused dispatch of k=4
    for i, c in enumerate(clients):
        arr = np.zeros(16, dtype=TRANSFER_DTYPE)
        arr["id_lo"] = np.arange(1000 + i * 16, 1016 + i * 16)
        arr["debit_account_id_lo"] = 1 + (np.arange(16) + i * 3) % 40
        arr["credit_account_id_lo"] = 1 + (np.arange(16) + i * 3 + 7) % 40
        arr["amount_lo"] = 1 + i
        arr["ledger"] = 1
        arr["code"] = 1
        c.request(Operation.create_transfers, arr.tobytes())
    cluster.network.run()
    r.pump_commits()
    # per-REPLICA counter (the kernels object is shared process-wide, so
    # its compile cache says nothing about THIS replica's behavior)
    assert r.group_stats["fused_ops"] > 0, "group commit never fused"
    r.flush_commits()
    cluster.network.run()
    for c in clients:
        h, reply = c.take_reply()
        assert reply == b"", reply  # all ok
    assert_matches_oracle(r, committed)


def test_fuse_window_holds_short_run_then_dispatches():
    """The group-commit fuse window: with earlier commits still in flight,
    a SHORT quorum-ready run of create_transfers defers (so arrivals
    within the window coalesce into one fused dispatch) and dispatches
    once the window expires. With the engine idle it never defers — the
    hold must not starve the engine or deadlock a quiet server."""
    from tigerbeetle_tpu.types import TRANSFER_DTYPE

    cluster = Cluster(replica_count=1)
    r = cluster.replicas[0]
    c1 = cluster.add_client()
    c2 = cluster.add_client()
    r.commit_window = 4
    assert r.fuse_window_ns > 0  # default on

    acc = np.zeros(8, dtype=types.ACCOUNT_DTYPE)
    acc["id_lo"] = np.arange(1, 9)
    acc["ledger"] = 1
    acc["code"] = 1
    c1.request(Operation.create_accounts, acc.tobytes())
    cluster.network.run()
    r.pump_commits()
    r.flush_commits()
    cluster.network.run()
    c1.take_reply()

    def xfer(base):
        arr = np.zeros(4, dtype=TRANSFER_DTYPE)
        arr["id_lo"] = np.arange(base, base + 4)
        arr["debit_account_id_lo"] = 1 + np.arange(4) % 8
        arr["credit_account_id_lo"] = 1 + (np.arange(4) + 3) % 8
        arr["amount_lo"] = 1
        arr["ledger"] = 1
        arr["code"] = 1
        return arr.tobytes()

    # engine idle (_inflight empty): the first batch dispatches at once
    base = r.commit_min
    c1.request(Operation.create_transfers, xfer(1000))
    cluster.network.run()
    r.pump_commits()
    assert r.commit_min == base + 1, "idle engine must not defer"
    assert r._fuse_started is None

    # engine busy (batch 1 un-flushed in _inflight): a short run defers
    c2.request(Operation.create_transfers, xfer(2000))
    cluster.network.run()
    r.pump_commits()
    assert r.commit_min == base + 1, "short run should hold while busy"
    assert r._fuse_started is not None

    # window expiry (one deterministic tick = 10 ms >> fuse_window_ns):
    # the held run dispatches
    cluster.time.tick()
    r.pump_commits()
    assert r.commit_min == base + 2
    assert r._fuse_started is None

    r.flush_commits()
    cluster.network.run()
    for c in (c1, c2):
        _h, reply = c.take_reply()
        assert reply == b"", reply


def test_standby_follows_without_voting():
    """A standby (reference: src/vsr/replica.zig:163-175) journals and
    commits the replicated stream but never acks or votes: quorums are
    formed by the active set alone, and after a view change the standby
    follows into the new view."""
    from tigerbeetle_tpu.vsr.header import Command, Header

    cluster = Cluster(replica_count=3, standby_count=1)
    standby = cluster.replicas[3]
    assert standby.standby

    acks_from_standby = []

    def sniff(src, dst, data):
        h = Header.from_bytes(data[:128])
        if src == 3 and h.command in (
            Command.prepare_ok, Command.start_view_change,
            Command.do_view_change,
        ):
            acks_from_standby.append(h.command)
        return True

    cluster.network.filters.append(sniff)
    client = cluster.add_client()
    gen = WorkloadGenerator(81)
    for op, body in _batch_bodies(gen, 4):
        cluster.execute(client, op, body)
    cluster.run_ticks(10)
    head = cluster.replicas[0].commit_min
    assert standby.commit_min == head  # followed the whole log
    assert_identical_state(cluster.replicas)  # incl. the standby
    assert not acks_from_standby  # never acked, never voted

    # primary fails: the ACTIVE set elects view 1; the standby follows
    cluster.detach_replica(0)
    cluster.run_ticks(80)
    live = cluster.replicas[1:3]
    assert all(r.status == "normal" and r.view == 1 for r in live)
    op, events = gen.gen_accounts_batch(16)
    body = types.accounts_to_np(events).tobytes()
    client.request(op, body)
    cluster.network.run()
    if client.reply is None:
        client.resend()
        cluster.network.run()
    client.take_reply()
    cluster.run_ticks(20)
    assert standby.view == 1 and standby.status == "normal"
    assert standby.commit_min == live[0].commit_min
    assert not acks_from_standby
    assert_identical_state(cluster.replicas[1:])
