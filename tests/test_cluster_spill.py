"""Multi-replica spill: the bounded-memory store under VSR.

Each replica owns a forest block area in its grid zone (layout
forest_blocks); commits spill identically on every replica (determinism),
checkpoints carry the spill meta, and state sync ships the forest blocks
so a lagging replica adopting a checkpoint gets the spilled tail too
(reference: src/vsr/sync.zig checkpoint shipping + trailers)."""

from tigerbeetle_tpu import types
from tigerbeetle_tpu.testing.cluster import Cluster
from tigerbeetle_tpu.testing.state_checker import assert_identical_state
from tigerbeetle_tpu.testing.workload import WorkloadGenerator

KNOBS = dict(
    ledgers=(1,),
    invalid_rate=0.0,
    conflict_rate=0.03,
    chain_rate=0.0,
    two_phase_rate=0.1,
    balancing_rate=0.0,
    limit_account_rate=0.0,
)


def _submit_transfers(cluster, client, gen, n_batches, size=96):
    for _ in range(n_batches):
        op, events = gen.gen_transfers_batch(size)
        cluster.execute(client, op, types.transfers_to_np(events).tobytes())


def test_cluster_spills_identically_and_syncs():
    cluster = Cluster(replica_count=3, grid_size=64 * 1024 * 1024,
                      forest_blocks=192)
    assert all(r.forest is not None for r in cluster.replicas)
    client = cluster.add_client()
    gen = WorkloadGenerator(51, **KNOBS)

    op, events = gen.gen_accounts_batch(60)
    cluster.execute(client, op, types.accounts_to_np(events).tobytes())
    _submit_transfers(cluster, client, gen, 30)

    # every replica spilled, deterministically the same
    for r in cluster.replicas:
        assert r.ledger.spill.stats["cycles"] >= 1, r.replica
    spilled_sets = [frozenset(r.ledger.spill.spilled) for r in cluster.replicas]
    assert spilled_sets[0] == spilled_sets[1] == spilled_sets[2]
    assert len(spilled_sets[0]) > 0
    assert_identical_state(cluster.replicas)  # extract() merges the tail

    # lag replica 2 beyond the WAL: >journal_slot_count ops while detached,
    # crossing a checkpoint (interval 60) that carries spill meta
    cluster.detach_replica(2)
    _submit_transfers(cluster, client, gen, 66)
    assert cluster.replicas[0].checkpoint_op > 0
    assert "spill" in cluster.replicas[0].superblock.state.meta

    cluster.reattach_replica(2)
    cluster.run_ticks(200)
    lagger = cluster.replicas[2]
    head = cluster.replicas[0].commit_min
    assert lagger.commit_min == head, (lagger.commit_min, head)
    assert_identical_state(cluster.replicas)
    # the synced replica's spilled tail matches (forest blocks shipped)
    assert frozenset(lagger.ledger.spill.spilled) == frozenset(
        cluster.replicas[0].ledger.spill.spilled
    )
