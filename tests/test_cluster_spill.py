"""Multi-replica spill: the bounded-memory store under VSR.

Each replica owns a forest block area in its grid zone (layout
forest_blocks); commits spill identically on every replica (determinism),
checkpoints carry the spill meta, and state sync ships the forest blocks
so a lagging replica adopting a checkpoint gets the spilled tail too
(reference: src/vsr/sync.zig checkpoint shipping + trailers)."""

from tigerbeetle_tpu import types
from tigerbeetle_tpu.testing.cluster import Cluster
from tigerbeetle_tpu.testing.state_checker import assert_identical_state
from tigerbeetle_tpu.testing.workload import WorkloadGenerator

KNOBS = dict(
    ledgers=(1,),
    invalid_rate=0.0,
    conflict_rate=0.03,
    chain_rate=0.0,
    two_phase_rate=0.1,
    balancing_rate=0.0,
    limit_account_rate=0.0,
)


def _submit_transfers(cluster, client, gen, n_batches, size=96):
    for _ in range(n_batches):
        op, events = gen.gen_transfers_batch(size)
        cluster.execute(client, op, types.transfers_to_np(events).tobytes())


def test_cluster_spills_identically_and_syncs():
    cluster = Cluster(replica_count=3, grid_size=64 * 1024 * 1024,
                      forest_blocks=192)
    assert all(r.forest is not None for r in cluster.replicas)
    client = cluster.add_client()
    gen = WorkloadGenerator(51, **KNOBS)

    op, events = gen.gen_accounts_batch(60)
    cluster.execute(client, op, types.accounts_to_np(events).tobytes())
    _submit_transfers(cluster, client, gen, 30)

    # every replica spilled, deterministically the same
    for r in cluster.replicas:
        assert r.ledger.spill.stats["cycles"] >= 1, r.replica
    spilled_sets = [frozenset(r.ledger.spill.spilled) for r in cluster.replicas]
    assert spilled_sets[0] == spilled_sets[1] == spilled_sets[2]
    assert len(spilled_sets[0]) > 0
    assert_identical_state(cluster.replicas)  # extract() merges the tail

    # lag replica 2 beyond the WAL: >journal_slot_count ops while detached,
    # crossing a checkpoint (interval 60) that carries spill meta
    cluster.detach_replica(2)
    _submit_transfers(cluster, client, gen, 66)
    assert cluster.replicas[0].checkpoint_op > 0
    assert "spill" in cluster.replicas[0].superblock.state.meta

    cluster.reattach_replica(2)
    cluster.run_ticks(200)
    lagger = cluster.replicas[2]
    head = cluster.replicas[0].commit_min
    assert lagger.commit_min == head, (lagger.commit_min, head)
    assert_identical_state(cluster.replicas)
    # the synced replica's spilled tail matches (forest blocks shipped)
    assert frozenset(lagger.ledger.spill.spilled) == frozenset(
        cluster.replicas[0].ledger.spill.spilled
    )


def test_chunked_state_sync_over_lossy_network():
    """The checkpoint image (snapshot blobs + forest blocks) exceeds one
    message: state sync must ship it in bounded chunks (reference:
    src/vsr/sync.zig:9-56) and survive chunk loss (the tick-cadence retry
    restarts the gather; received chunks are kept)."""
    from tigerbeetle_tpu.constants import ConfigCluster
    from tigerbeetle_tpu.vsr.header import Command, Header

    small = ConfigCluster(
        message_size_max=1 << 18,  # 256 KiB: forces a multi-chunk image
        journal_slot_count=64, lsm_batch_multiple=4,
    )
    cluster = Cluster(replica_count=3, cluster=small,
                      grid_size=64 * 1024 * 1024, forest_blocks=192)
    client = cluster.add_client()
    gen = WorkloadGenerator(53, **KNOBS)
    op, events = gen.gen_accounts_batch(60)
    cluster.execute(client, op, types.accounts_to_np(events).tobytes())
    _submit_transfers(cluster, client, gen, 10)

    # drop every 4th sync chunk: the transfer must self-heal
    drops = {"n": 0}

    def lossy(src, dst, data):
        h = Header.from_bytes(data[:128])
        if h.command == Command.sync_manifest:
            drops["n"] += 1
            if drops["n"] % 4 == 0:
                return False
        # every frame respects the cluster's message size bound
        assert len(data) <= small.message_size_max, len(data)
        return True

    cluster.network.filters.append(lossy)

    cluster.detach_replica(2)
    _submit_transfers(cluster, client, gen, 70)  # beyond the 64-slot WAL
    r0 = cluster.replicas[0]
    assert r0.checkpoint_op > 0
    image, _cksum = r0._sync_checkpoint_payload()
    assert len(image) > small.message_size_max  # genuinely multi-chunk

    cluster.reattach_replica(2)
    cluster.run_ticks(400)
    lagger = cluster.replicas[2]
    assert lagger.commit_min == r0.commit_min, (
        lagger.commit_min, r0.commit_min,
    )
    assert drops["n"] > 4  # chunked transfer actually happened (and lost some)
    assert_identical_state(cluster.replicas)


def test_grid_block_repair_from_peers():
    """A corrupt forest block on ONE replica heals from a peer's intact
    copy — scrub detects it, request_blocks/block repairs it, and no full
    state sync is needed (reference: src/vsr/grid_blocks_missing.zig,
    src/vsr/grid.zig:731)."""
    from tigerbeetle_tpu.io.storage import Zone
    from tigerbeetle_tpu.vsr.header import Command, Header

    cluster = Cluster(replica_count=3, grid_size=64 * 1024 * 1024,
                      forest_blocks=192)
    client = cluster.add_client()
    gen = WorkloadGenerator(55, **KNOBS)
    op, events = gen.gen_accounts_batch(60)
    cluster.execute(client, op, types.accounts_to_np(events).tobytes())
    _submit_transfers(cluster, client, gen, 30)
    r1 = cluster.replicas[1]
    assert r1.ledger.spill.stats["cycles"] >= 1

    syncs = {"n": 0}

    def count_syncs(src, dst, data):
        h = Header.from_bytes(data[:128])
        if h.command == Command.sync_manifest:
            syncs["n"] += 1
        return True

    cluster.network.filters.append(count_syncs)

    # The spilled volume may still sit in queued insert jobs (the deferred
    # spill-IO executor) or tree memtables; drain + flush every replica
    # identically (a deterministic local storage action) so the forest
    # holds real grid blocks to corrupt and repair.
    for r in cluster.replicas:
        r.ledger.spill.io_drain()
        for tree in (r.forest.transfers, r.forest.posted):
            tree.flush()

    grid = r1.forest.grid
    addr = next(
        a for a in range(1, grid.block_count + 1)
        if not grid.free_set.is_free(a)
    )
    cluster.storages[1].fault(Zone.grid, grid._pos(addr) + 40, 64)
    assert not grid.verify_block(addr)

    cluster.run_ticks(
        8 * ((grid.block_count + 7) // 8 // 8 + 4)  # full scrub rotation
    )
    assert grid.verify_block(addr), "block not healed"
    assert not r1._grid_missing
    assert syncs["n"] == 0, "healed via state sync, not block repair"

    # the healed replica serves commits normally and state stays identical
    _submit_transfers(cluster, client, gen, 2)
    assert_identical_state(cluster.replicas)


def test_wrong_content_repair_refused_heals_from_honest_peer():
    """A diverged peer serves a block whose bytes are VALID (good
    self-checksum) but belong to a different address. The victim's
    identity registry must refuse the install and keep asking until the
    honest peer serves the right block; the diverged peer's own scrub
    must then detect ITS wrong-content block (identity mismatch, not
    checksum) and heal it back from the cluster — the silent-corruption
    scenario address-based repair alone cannot catch."""
    from tigerbeetle_tpu.io.storage import Zone

    cluster = Cluster(replica_count=3, grid_size=64 * 1024 * 1024,
                      forest_blocks=192)
    client = cluster.add_client()
    gen = WorkloadGenerator(77, **KNOBS)
    op, events = gen.gen_accounts_batch(60)
    cluster.execute(client, op, types.accounts_to_np(events).tobytes())
    _submit_transfers(cluster, client, gen, 30)
    for r in cluster.replicas:
        r.ledger.spill.io_drain()  # queued deferred inserts land first
        for tree in (r.forest.transfers, r.forest.posted):
            tree.flush()

    r1 = cluster.replicas[1]  # victim
    r0 = cluster.replicas[0]  # "diverged" peer
    grid1 = r1.forest.grid
    acquired = [
        a for a in range(1, grid1.block_count + 1)
        if not grid1.free_set.is_free(a)
    ]
    addr, donor = acquired[0], acquired[1]

    # victim: plain corruption at addr
    cluster.storages[1].fault(Zone.grid, grid1._pos(addr) + 40, 64)
    assert not grid1.verify_block(addr)
    # diverged peer: ITS addr holds a valid-checksum block copied from a
    # DIFFERENT address (layout divergence in miniature)
    grid0 = r0.forest.grid
    wrong = grid0.read_block_raw(donor)
    cluster.storages[0].write(
        Zone.grid, grid0._pos(addr), wrong
    )
    grid0.cache.remove(addr)
    assert not grid0.verify_block(addr), "identity check missed the swap"

    cluster.run_ticks(8 * ((grid1.block_count + 7) // 8 // 8 + 8))

    # the victim healed with the RIGHT content (never the diverged bytes)
    assert grid1.verify_block(addr), "victim not healed"
    assert not r1._grid_missing
    # the diverged peer's scrub found its own wrong-content block and
    # healed it back from the cluster
    assert grid0.verify_block(addr), "diverged peer not healed"

    _submit_transfers(cluster, client, gen, 2)
    assert_identical_state(cluster.replicas)


def test_spilling_replica_keeps_committing_deterministically():
    """The determinism proof for lifting spill_async_io: with the replica's
    spill/grid IO on the deferred executor (queued at the commit, run at
    the tick boundary — vsr/replica.py), a cluster whose replicas are
    ACTIVELY spilling keeps committing client batches, the cross-replica
    state checker stays green, and two identical runs produce identical
    commit histories and spilled sets (grid layouts included — repair-by-
    address depends on it)."""

    def run_once():
        cluster = Cluster(replica_count=3, grid_size=64 * 1024 * 1024,
                          forest_blocks=192)
        histories = [[] for _ in cluster.replicas]
        for r, h in zip(cluster.replicas, histories):
            r.commit_hook = (
                lambda header, body, _h=h: _h.append(
                    (header.op, header.checksum)
                )
            )
        client = cluster.add_client()
        gen = WorkloadGenerator(91, **KNOBS)
        op, events = gen.gen_accounts_batch(60)
        cluster.execute(client, op, types.accounts_to_np(events).tobytes())
        _submit_transfers(cluster, client, gen, 30)
        cluster.run_ticks(4)  # tick pumps drain the deferred insert queue

        # every replica is actively spilling — and the deferred executor
        # really is the one in use (inserts queue rather than run inline)
        from tigerbeetle_tpu.models.spill import DeferredSpillIO

        for r in cluster.replicas:
            assert isinstance(r.ledger.spill._io, DeferredSpillIO)
            assert r.ledger.spill.stats["cycles"] >= 1, r.replica

        # a spilling cluster KEEPS committing: every further batch gets a
        # reply and commit_min advances in lockstep
        head_before = cluster.replicas[0].commit_min
        _submit_transfers(cluster, client, gen, 6)
        cluster.run_ticks(8)
        heads = {r.commit_min for r in cluster.replicas}
        assert len(heads) == 1 and heads.pop() > head_before
        assert_identical_state(cluster.replicas)

        spilled = [frozenset(r.ledger.spill.spilled) for r in cluster.replicas]
        assert spilled[0] == spilled[1] == spilled[2]
        assert len(spilled[0]) > 0
        # grid-layout determinism across replicas: acquired address sets
        # (and their registry checksums) must be identical
        for r in cluster.replicas:
            r.ledger.spill.io_drain()
        grids = [
            tuple(sorted(
                (a, r.forest.grid.block_chk.get(a, 0))
                for a in range(1, r.forest.grid.block_count + 1)
                if not r.forest.grid.free_set.is_free(a)
            ))
            for r in cluster.replicas
        ]
        assert grids[0] == grids[1] == grids[2]
        return histories[0], spilled[0], grids[0]

    run_a = run_once()
    run_b = run_once()
    assert run_a[0] == run_b[0], "commit history diverged across same runs"
    assert run_a[1] == run_b[1], "spilled set diverged across same runs"
    assert run_a[2] == run_b[2], "grid layout diverged across same runs"
