"""Ingress gateway tests (tigerbeetle_tpu/ingress + the bus front door):
session multiplexing over shared connections, credit-based admission with
typed busy sheds, fair pumping against firehose/slow-loris peers, pool
credit on close, accept drain, the CDC fan-out hub's backpressure
isolation, the many-session client-table checkpoint blob, and the
multiplexed front door end-to-end (500-session tier-1 smoke, 10k soak
nightly)."""

from __future__ import annotations

import errno
import socket
import time

import numpy as np
import pytest

from tigerbeetle_tpu import types
from tigerbeetle_tpu.types import Operation
from tigerbeetle_tpu.vsr.header import HEADER_SIZE, Command, Header


def _request_frame(cid: int, request: int = 0,
                   operation: int = int(Operation.register),
                   body: bytes = b"") -> bytes:
    h = Header(
        command=int(Command.request), client=cid, request=request,
        operation=operation,
    )
    h.set_checksum_body(body)
    h.set_checksum()
    return h.to_bytes() + body


def _listening_bus(**kw):
    from tigerbeetle_tpu.benchmark import free_port
    from tigerbeetle_tpu.io.message_bus import TCPMessageBus
    from tigerbeetle_tpu.metrics import Metrics

    port = free_port()
    bus = TCPMessageBus([("127.0.0.1", port)], 0, listen=True, **kw)
    bus.metrics = Metrics()
    return bus, port


# ---------------------------------------------------------------------
# transport front door (io/message_bus.py)
# ---------------------------------------------------------------------


def test_accept_drain_takes_a_connect_storm_in_one_pump():
    """One readiness event used to land ONE accept per select round; the
    drain loop takes the whole storm inside one pump turn."""
    bus, port = _listening_bus()
    socks = [socket.create_connection(("127.0.0.1", port)) for _ in range(40)]
    try:
        deadline = time.monotonic() + 5
        pumps = 0
        while len(bus._links) < 40 and time.monotonic() < deadline:
            bus.pump(timeout=0.05)
            pumps += 1
        assert len(bus._links) == 40
        # the storm needed O(1) pump turns, not one per connection
        assert pumps <= 4, pumps
        snap = bus.metrics.snapshot()["counters"]
        assert snap["ingress.accepts"] == 40
    finally:
        for s in socks:
            s.close()
        bus.sel.close()


def test_slow_loris_and_torn_header_do_not_stall_other_sessions():
    """A peer trickling a frame byte-by-byte (or closing mid-frame)
    costs bounded work; complete frames from other connections dispatch
    within the same pump turn."""
    got: list[tuple[int, int]] = []  # (client id, request)

    bus, port = _listening_bus()
    bus.attach(0, lambda src, frame: got.append((
        int.from_bytes(frame[48:64], "little"),
        int.from_bytes(frame[80:84], "little"),
    )))
    loris = socket.create_connection(("127.0.0.1", port))
    torn = socket.create_connection(("127.0.0.1", port))
    fast = socket.create_connection(("127.0.0.1", port))
    try:
        frame_l = _request_frame(0x10A15, 1)
        loris.sendall(frame_l[:3])  # 3 bytes of header, then silence
        torn.sendall(_request_frame(0x70A2, 1)[: HEADER_SIZE // 2])
        fast.sendall(_request_frame(0xFA57, 1))
        deadline = time.monotonic() + 5
        while not got and time.monotonic() < deadline:
            bus.pump(timeout=0.05)
        # the fast session dispatched despite two wedged partial frames
        assert (0xFA57, 1) in got
        # the torn peer closes mid-frame: no dispatch, no crash
        torn.close()
        for _ in range(3):
            bus.pump(timeout=0.02)
        assert all(cid != 0x70A2 for cid, _r in got)
        # the loris eventually completes its frame: it dispatches then
        for i in range(3, len(frame_l), 7):
            loris.sendall(frame_l[i : i + 7])
            bus.pump(timeout=0.0)
        deadline = time.monotonic() + 5
        while (0x10A15, 1) not in got and time.monotonic() < deadline:
            bus.pump(timeout=0.05)
        assert (0x10A15, 1) in got
    finally:
        loris.close()
        fast.close()
        bus.sel.close()


def test_dispatch_budget_firehose_fairness():
    """A firehose peer's frames past the per-connection budget stay
    buffered (drained first next turn) while another peer's single frame
    dispatches in the same turn."""
    got: list[int] = []
    bus, port = _listening_bus(dispatch_budget=4)
    bus.attach(0, lambda src, frame: got.append(
        int.from_bytes(frame[48:64], "little")
    ))
    hose = socket.create_connection(("127.0.0.1", port))
    meek = socket.create_connection(("127.0.0.1", port))
    try:
        hose.sendall(b"".join(
            _request_frame(0xF00D, r) for r in range(1, 11)
        ))
        meek.sendall(_request_frame(0x3EE, 1))
        deadline = time.monotonic() + 5
        while 0x3EE not in got and time.monotonic() < deadline:
            bus.pump(timeout=0.05)
        # the meek peer was served while the firehose still had frames
        # buffered past its budget
        assert got.count(0xF00D) <= 2 * 4
        # leftovers drain over the following turns, budget per turn
        deadline = time.monotonic() + 5
        while got.count(0xF00D) < 10 and time.monotonic() < deadline:
            bus.pump(timeout=0.05)
        assert got.count(0xF00D) == 10
    finally:
        hose.close()
        meek.close()
        bus.sel.close()


def test_message_pool_typed_outcomes_and_credit_on_close():
    """Pool exhaustion is a typed outcome, not a silent drop — and a
    closing connection credits its unsent bytes back (a churned client
    cannot leak budget)."""
    import threading

    from tigerbeetle_tpu.io.message_bus import TCPMessageBus
    from tigerbeetle_tpu.metrics import Metrics

    # plain TCP sink: accepts, never reads
    srv = socket.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(4)
    port = srv.getsockname()[1]
    accepted = []
    threading.Thread(
        target=lambda: accepted.append(srv.accept()[0]), daemon=True
    ).start()

    bus = TCPMessageBus(
        [("127.0.0.1", port)], 0xC11E27,
        messages_max=2, message_size_max=1024,
    )
    bus.metrics = Metrics()
    try:
        # resolve the non-blocking dial (flushes the hello frame) so the
        # per-connection cap below is measured on OUR payloads alone
        bus.send(0xC11E27, 0, b"")
        conn = bus.conns[0]
        deadline = time.monotonic() + 5
        while (
            (not conn.connected or conn.wbuf)
            and time.monotonic() < deadline
        ):
            bus.pump(timeout=0.05)
        assert conn.connected and not conn.wbuf
        # small sends stay buffered (below FLUSH_EAGER): the pool charge
        # is held until flush or close
        assert bus.send(0xC11E27, 0, b"x" * 1024) == "sent"
        assert bus.pool.used == 1024
        # shrink the shared budget below the NEXT send (the per-conn cap
        # still has room): exhaustion must come back typed as shed_pool
        bus.pool.capacity = 1500
        out = bus.send(0xC11E27, 0, b"y" * 1024)
        assert out == "shed_pool"
        snap = bus.metrics.snapshot()["counters"]
        assert snap["ingress.shed_pool"] == 1
        assert bus.pool.used == 1024  # the refused send charged nothing
        bus._close(conn)
        assert bus.pool.used == 0  # credited on close, not leaked
    finally:
        bus.sel.close()
        srv.close()


def test_wedged_client_consumer_disconnected_after_strikes():
    """A CLIENT connection pinned at its send cap (open socket, never
    reads) accumulates strikes and is cut; its pool bytes return."""
    got = []
    bus, port = _listening_bus(wedged_strikes_max=3)
    bus.attach(0, lambda src, frame: got.append(frame))
    peer = socket.create_connection(("127.0.0.1", port))
    try:
        cid = 0x3EDCED
        peer.sendall(_request_frame(cid, 1))
        deadline = time.monotonic() + 5
        while not got and time.monotonic() < deadline:
            bus.pump(timeout=0.05)
        conn = bus.conns[cid]

        class _EAgainSock:
            def send(self, data):
                raise OSError(errno.EAGAIN, "wedged")

            def close(self):
                pass

        real_sock = conn.sock
        conn.sock = _EAgainSock()
        # fill the per-connection cap, then strike it out
        chunk = b"r" * (1 << 18)
        while bus.send(0, cid, chunk) == "sent":
            bus._flush(conn)  # EAGAIN: nothing leaves, wbuf grows
        outcomes = [bus.send(0, cid, chunk) for _ in range(5)]
        # refusals strike the wedged peer out; past the limit the conn is
        # gone and later sends see "unreachable"
        assert outcomes[0] == "shed_conn"
        assert outcomes[-1] == "unreachable"
        assert cid not in bus.conns  # disconnected at the strike limit
        assert bus.pool.used == 0
        snap = bus.metrics.snapshot()["counters"]
        assert snap["ingress.disconnect_wedged"] == 1
        real_sock.close()
    finally:
        peer.close()
        bus.sel.close()


def test_session_multiplexing_two_sessions_share_one_connection():
    """Two logical sessions' Clients on ONE demux bus/connection: the
    server aliases reply routing per client id; each Client sees only
    its own replies."""
    from tigerbeetle_tpu.io.message_bus import TCPMessageBus
    from tigerbeetle_tpu.vsr.client import Client

    server, port = _listening_bus()
    sessions_granted = {}

    def serve(src, frame):
        h = Header.from_bytes(frame[:HEADER_SIZE])
        if h.command != Command.request:
            return
        session = sessions_granted.setdefault(
            h.client, 100 + len(sessions_granted)
        )
        body = session.to_bytes(8, "little")
        r = Header(
            command=int(Command.reply), client=h.client,
            request=h.request, operation=h.operation, op=session,
        )
        r.set_checksum_body(body)
        r.set_checksum()
        server.send(0, h.client, r.to_bytes() + body)

    server.attach(0, serve)
    mux = TCPMessageBus([("127.0.0.1", port)], 0xD3FACE, demux=True)
    try:
        a = Client(0xA11CE, mux, replica_count=1)
        b = Client(0xB0B, mux, replica_count=1)
        a.register()
        b.register()
        deadline = time.monotonic() + 5
        while (
            (a.reply is None or b.reply is None)
            and time.monotonic() < deadline
        ):
            server.pump(timeout=0.0)
            mux.pump(timeout=0.01)
        a.take_reply()
        b.take_reply()
        assert {a.session, b.session} == {100, 101}
        # ONE server-side connection carries both sessions' aliases
        # (plus the mux bus's own hello-peer id)
        conns = [c for c in server._links if c.sessions]
        assert len(conns) == 1
        assert conns[0].sessions >= {0xA11CE, 0xB0B}
    finally:
        mux.sel.close()
        server.sel.close()


# ---------------------------------------------------------------------
# admission control (ingress/gateway.py + regulator.py)
# ---------------------------------------------------------------------


def _oracle_cluster(metrics=None):
    from tigerbeetle_tpu.metrics import Metrics
    from tigerbeetle_tpu.models.oracle import OracleStateMachine
    from tigerbeetle_tpu.testing.cluster import Cluster

    m = metrics or Metrics()
    return Cluster(
        replica_count=1, backend_factory=OracleStateMachine, metrics=m
    ), m


def _accounts(ids):
    arr = np.zeros(len(ids), dtype=types.ACCOUNT_DTYPE)
    arr["id_lo"] = ids
    arr["ledger"] = 1
    arr["code"] = 1
    return arr.tobytes()


def _transfer(tid: int) -> bytes:
    arr = np.zeros(1, dtype=types.TRANSFER_DTYPE)
    arr["id_lo"] = tid
    arr["debit_account_id_lo"] = 1
    arr["credit_account_id_lo"] = 2
    arr["amount_lo"] = 1
    arr["ledger"] = 1
    arr["code"] = 1
    return arr.tobytes()


def test_gateway_sheds_typed_busy_and_recovers():
    from tigerbeetle_tpu.ingress import IngressGateway

    cluster, m = _oracle_cluster()
    r = cluster.replicas[0]
    gw = IngressGateway(cluster.network, r)
    gw.install()
    c = cluster.add_client()
    _h, body = cluster.execute(c, Operation.create_accounts, _accounts([1, 2]))
    assert body == b""

    # saturate: occupancy at the cap -> the next NEW request sheds with
    # a typed busy reply echoing client + request
    orig = r.ingress_occupancy
    r.ingress_occupancy = lambda: (99, 8)
    gw.regulator.drain()
    c.request(Operation.create_transfers, _transfer(50))
    cluster.network.run()
    assert c.reply is None
    assert c.busy and c.busy_replies == 1
    assert c.in_flight is not None  # the same bytes retry after backoff
    snap = m.snapshot()["counters"]
    assert snap["ingress.shed"] == 1

    # capacity returns: the RESEND of the same request is admitted and
    # commits exactly once
    r.ingress_occupancy = orig
    gw.regulator.drain()
    c.resend()
    cluster.network.run()
    _h, body = c.take_reply()
    assert body == b""
    assert m.snapshot()["counters"]["ingress.shed"] == 1


def test_gateway_never_sheds_retransmits():
    """A retransmit of an ADMITTED request bypasses admission even under
    saturation: the replica dedups it for free (cached-reply resend),
    and shedding it would stall the client's reply recovery."""
    from tigerbeetle_tpu.ingress import IngressGateway

    cluster, m = _oracle_cluster()
    r = cluster.replicas[0]
    gw = IngressGateway(cluster.network, r)
    gw.install()
    c = cluster.add_client()
    _h, body = cluster.execute(c, Operation.create_accounts, _accounts([1, 2]))
    assert body == b""
    c.request(Operation.create_transfers, _transfer(51))
    cluster.network.run()
    _h, body = c.take_reply()
    assert body == b""
    before = m.snapshot()["counters"]["ingress.shed"]

    r.ingress_occupancy = lambda: (99, 8)  # fully saturated
    gw.regulator.drain()
    # a duplicate of the last request (reply lost scenario): must reach
    # the replica and come back with the CACHED reply, not a busy —
    # rebuild the exact duplicate wire (same request number, same body)
    h = Header(
        command=int(Command.request),
        operation=int(Operation.create_transfers),
        client=c.client_id, context=c.session, request=c.request_number,
    )
    body_t = _transfer(51)
    h.set_checksum_body(body_t)
    h.set_checksum()
    wire = h.to_bytes() + body_t
    cluster.network.send(c.client_id, 0, wire)
    c.in_flight = wire  # make the client accept the (cached) reply
    cluster.network.run()
    snap = m.snapshot()["counters"]
    assert snap["ingress.shed"] == before  # no shed
    assert snap["ingress.retransmits"] >= 1
    _h, body = c.take_reply()
    assert body == b""


def test_gateway_session_cap_sheds_new_sessions_only():
    from tigerbeetle_tpu.ingress import IngressGateway
    from tigerbeetle_tpu.metrics import Metrics
    from tigerbeetle_tpu.models.oracle import OracleStateMachine
    from tigerbeetle_tpu.testing.cluster import Cluster
    from tigerbeetle_tpu.vsr.client import Client

    m = Metrics()
    cluster = Cluster(
        replica_count=1, backend_factory=OracleStateMachine, metrics=m
    )
    r = cluster.replicas[0]
    gw = IngressGateway(cluster.network, r, sessions_max=2)
    gw.install()
    a = cluster.add_client()
    b = cluster.add_client()
    over = Client(1 << 70, cluster.network, 1)
    over.register()
    cluster.network.run()
    assert over.session == 0 and over.busy  # shed at the session cap
    snap = m.snapshot()["counters"]
    assert snap["ingress.shed_sessions"] == 1
    # existing sessions keep working
    _h, body = cluster.execute(a, Operation.create_accounts, _accounts([1, 2]))
    assert body == b""
    _h, body = cluster.execute(b, Operation.create_transfers, _transfer(52))
    assert body == b""


def test_replica_eviction_frees_gateway_session_slot():
    """A register at clients_max evicts the oldest session from the
    replica AND (via ingress_evict_hook) from the gateway table:
    evicted sessions on a still-open multiplexed connection must not
    pin the sessions_max cap forever."""
    from tigerbeetle_tpu.constants import ConfigCluster
    from tigerbeetle_tpu.ingress import IngressGateway
    from tigerbeetle_tpu.metrics import Metrics
    from tigerbeetle_tpu.models.oracle import OracleStateMachine
    from tigerbeetle_tpu.testing.cluster import Cluster

    m = Metrics()
    cfg = ConfigCluster(clients_max=2)
    cluster = Cluster(
        replica_count=1, cluster=cfg,
        backend_factory=OracleStateMachine, metrics=m,
    )
    r = cluster.replicas[0]
    gw = IngressGateway(cluster.network, r, sessions_max=3)
    gw.install()
    # each register past clients_max evicts the oldest; the gateway
    # table must track, so none of these is shed at the gateway cap
    # (add_client asserts the register got a real session)
    for _ in range(4):
        cluster.add_client()
    snap = m.snapshot()["counters"]
    assert snap.get("ingress.shed_sessions", 0) == 0
    assert set(gw.sessions) == set(r.client_table)
    assert len(gw.sessions) == 2


def test_duplicate_register_commit_releases_replaced_reply_slot():
    """A register op for a client ALREADY in the table (a view change
    can carry the same client's register twice in the surviving log)
    overwrites the entry; the replaced entry's reply slot must return
    to the free list — the old O(sessions) rebuild self-healed this,
    the incremental list has to be told."""
    from tigerbeetle_tpu.constants import ConfigCluster
    from tigerbeetle_tpu.models.oracle import OracleStateMachine
    from tigerbeetle_tpu.testing.cluster import Cluster

    cfg = ConfigCluster(clients_max=8, client_reply_slots=2)
    cluster = Cluster(
        replica_count=1, cluster=cfg, backend_factory=OracleStateMachine
    )
    r = cluster.replicas[0]
    a = cluster.add_client()
    b = cluster.add_client()
    assert r.client_table[a.client_id]["slot"] is not None
    assert r.client_table[b.client_id]["slot"] is not None

    def dup_register(client_id):
        h = Header(
            command=int(Command.prepare), client=client_id,
            operation=int(Operation.register), op=r.op + 1,
            timestamp=r.sm.prepare_timestamp + 1,
        )
        r._commit_finalize(r._commit_dispatch(h, b""))

    def assert_slot_conservation():
        # every slot is either owned by a table entry or on the free
        # list — never both, never neither
        used = {e.get("slot") for e in r.client_table.values()} - {None}
        free = set(r._reply_slots_free or [])
        assert used.isdisjoint(free)
        assert used | free == set(range(cfg.client_reply_slots)), (
            used, free,
        )

    dup_register(a.client_id)
    assert_slot_conservation()
    assert r.client_table[a.client_id]["slot"] is not None

    # restart edge: the free list is rebuilt LAZILY (None until the
    # first alloc) — a duplicate register replayed from the WAL tail
    # before any rebuild must not let the lazy rebuild count the
    # replaced entry's slot as owned
    r._reply_slots_free = None
    dup_register(b.client_id)
    assert_slot_conservation()
    assert r.client_table[b.client_id]["slot"] is not None


def test_conn_close_drops_gateway_sessions_only_for_that_conn():
    """The bus notifies the gateway BEFORE clearing a closing
    connection's session aliases; the gateway drops exactly those
    sessions' records."""
    from tigerbeetle_tpu.io.message_bus import TCPMessageBus
    from tigerbeetle_tpu.ingress import IngressGateway

    class _FakeReplica:
        replica = 0
        is_primary = True  # the gateway only admits on the primary

        def __init__(self, bus):
            from tigerbeetle_tpu.metrics import Metrics

            self.metrics = Metrics()
            self.network = bus

        def ingress_occupancy(self):
            return (0, 8)

        def _send(self, dst, header):
            pass

    server, port = _listening_bus()
    server.attach(0, lambda src, frame: None)
    fake = _FakeReplica(server)
    gw = IngressGateway(server, fake)
    gw.install()
    s1 = socket.create_connection(("127.0.0.1", port))
    s2 = socket.create_connection(("127.0.0.1", port))
    try:
        s1.sendall(_request_frame(0xAA1, 1) + _request_frame(0xAA2, 1))
        s2.sendall(_request_frame(0xBB1, 1))
        deadline = time.monotonic() + 5
        while len(gw.sessions) < 3 and time.monotonic() < deadline:
            server.pump(timeout=0.05)
        assert set(gw.sessions) == {0xAA1, 0xAA2, 0xBB1}
        s1.close()
        deadline = time.monotonic() + 5
        while len(gw.sessions) > 1 and time.monotonic() < deadline:
            server.pump(timeout=0.05)
        assert set(gw.sessions) == {0xBB1}
    finally:
        s2.close()
        server.sel.close()


# ---------------------------------------------------------------------
# CDC fan-out hub (ingress/fanout.py)
# ---------------------------------------------------------------------


def test_fanout_eight_consumers_throttled_pauses_only_itself():
    from tigerbeetle_tpu.cdc import MemoryCursor, MemorySink
    from tigerbeetle_tpu.ingress import CdcFanoutHub

    cluster, m = _oracle_cluster()
    r = cluster.replicas[0]
    hub = CdcFanoutHub(r, window=8)  # small window: laggards hit the WAL
    sinks = {f"c{i}": MemorySink() for i in range(8)}
    slow = MemorySink(capacity=4)
    sinks["slow"] = slow
    for name, sink in sinks.items():
        hub.add_consumer(name, sink, MemoryCursor(), ack_interval=4)
    hub.attach()

    c = cluster.add_client()
    _h, body = cluster.execute(c, Operation.create_accounts, _accounts([1, 2]))
    assert body == b""
    for i in range(24):
        _h, body = cluster.execute(
            c, Operation.create_transfers, _transfer(100 + i)
        )
        assert body == b""
        hub.pump(budget_ops=4)
    for _ in range(40):
        hub.pump(budget_ops=8)
    lags = hub.lag_ops()
    assert lags["slow"] > 0, lags  # the throttled consumer lags...
    assert all(v == 0 for k, v in lags.items() if k != "slow"), lags
    # ...past the live window: its reads fell back to the WAL ring
    assert m.snapshot()["counters"]["cdc.journal_reads"] > 0
    # fast consumers carry identical streams
    first = sinks["c0"].lines
    assert first and all(
        sinks[f"c{i}"].lines == first for i in range(1, 8)
    )
    # drain the slow one: it converges with the same stream
    while hub.lag_ops()["slow"]:
        slow.drain()
        hub.pump(budget_ops=16)
    slow.drain()
    gauges = m.snapshot()["gauges"]
    assert gauges["ingress.fanout_consumers"] == 9
    assert gauges["ingress.fanout_lag_ops"] == 0


def test_fanout_consumer_resumes_from_cursor():
    """Removing and re-adding a consumer (a crash model: hub state
    volatile, cursor durable) redelivers only from its last ack."""
    from tigerbeetle_tpu.cdc import MemoryCursor, MemorySink
    from tigerbeetle_tpu.ingress import CdcFanoutHub

    cluster, _m = _oracle_cluster()
    r = cluster.replicas[0]
    hub = CdcFanoutHub(r, window=64)
    cur = MemoryCursor()
    sink = MemorySink()
    hub.add_consumer("a", sink, cur, ack_interval=2)
    hub.attach()
    c = cluster.add_client()
    cluster.execute(c, Operation.create_accounts, _accounts([1, 2]))
    for i in range(6):
        cluster.execute(c, Operation.create_transfers, _transfer(300 + i))
    hub.pump(budget_ops=64)
    n_before = len(sink.lines)
    assert n_before > 0
    hub.remove_consumer("a")
    sink2 = MemorySink()
    hub.add_consumer("a", sink2, cur, ack_interval=2)
    for i in range(3):
        cluster.execute(c, Operation.create_transfers, _transfer(400 + i))
    for _ in range(10):
        hub.pump(budget_ops=64)
    # resumed from the durable cursor: at most the unacked tail redelivers
    assert 3 <= len(sink2.lines) <= 3 + 2


def test_cdc_tail_detach_leaves_later_tails_attached():
    """Two independent tails on one replica (e.g. a sim consumer next
    to a fan-out hub) chain through cdc_hook. Detaching EITHER one must
    splice only itself out — restoring a stale saved hook would
    silently unhook the tail that attached after it."""
    from tigerbeetle_tpu.cdc.pump import CdcTail

    cluster, _m = _oracle_cluster()
    r = cluster.replicas[0]
    c = cluster.add_client()
    cluster.execute(c, Operation.create_accounts, _accounts([1, 2]))

    # first-attached detaches first: the later tail must stay hooked
    t1 = CdcTail(r, window=16)
    t2 = CdcTail(r, window=16)
    t1.attach()
    t2.attach()
    t1.detach()
    cluster.execute(c, Operation.create_transfers, _transfer(500))
    assert t2._live, "later tail was unhooked by the earlier detach"
    assert not t1._live
    t2.detach()
    assert r.cdc_hook is None

    # last-attached detaches first: plain head restore
    t3 = CdcTail(r, window=16)
    t4 = CdcTail(r, window=16)
    t3.attach()
    t4.attach()
    t4.detach()
    cluster.execute(c, Operation.create_transfers, _transfer(501))
    assert t3._live
    assert not t4._live
    t3.detach()
    assert r.cdc_hook is None


# ---------------------------------------------------------------------
# many-session checkpoint (client-table grid blob)
# ---------------------------------------------------------------------


def test_client_table_blob_checkpoint_survives_restart():
    """600 sessions overflow the inline superblock budget: the table
    spills to a grid blob, restores across restart, and durable reply
    slots stay capped at client_reply_slots."""
    from tigerbeetle_tpu.constants import ConfigCluster
    from tigerbeetle_tpu.models.oracle import OracleStateMachine
    from tigerbeetle_tpu.testing.cluster import Cluster

    cfg = ConfigCluster(
        journal_slot_count=2048, clients_max=2000, client_reply_slots=8
    )
    cluster = Cluster(
        replica_count=1, cluster=cfg, backend_factory=OracleStateMachine
    )
    r = cluster.replicas[0]
    clients = [cluster.add_client() for _ in range(600)]
    _h, body = cluster.execute(
        clients[0], Operation.create_accounts, _accounts([1, 2])
    )
    assert body == b""
    r.checkpoint()
    st = r.superblock.state
    assert st.meta.get("client_table_blob") is True
    assert any(ref.name == "client_table" for ref in st.blobs)
    assert "client_table" not in st.meta
    r2 = cluster.restart_replica(0)
    assert len(r2.client_table) == 600
    slots = [
        e.get("slot") for e in r2.client_table.values()
        if e.get("slot") is not None
    ]
    assert len(slots) <= 8
    # a pre-restart session still works after the blob restore
    _h, body = cluster.execute(
        clients[5], Operation.create_transfers, _transfer(77)
    )
    assert body == b""


def test_small_client_table_stays_inline():
    from tigerbeetle_tpu.models.oracle import OracleStateMachine
    from tigerbeetle_tpu.testing.cluster import Cluster

    cluster = Cluster(replica_count=1, backend_factory=OracleStateMachine)
    r = cluster.replicas[0]
    cluster.add_client()
    r.checkpoint()
    st = r.superblock.state
    assert not st.meta.get("client_table_blob")
    assert "client_table" in st.meta
    assert not any(ref.name == "client_table" for ref in st.blobs)


# ---------------------------------------------------------------------
# deterministic simulator: fan-out + storm + gateway
# ---------------------------------------------------------------------


def test_simulator_ingress_fanout_storm_deterministic():
    """A seeded run with the gateway on every replica, a connect storm,
    and 3 fan-out consumers (one throttled): every consumer passes the
    full stream contract (the sim's checker), the throttled consumer's
    lag dominates, and two same-seed runs are byte-identical."""
    from tigerbeetle_tpu.testing.simulator import Simulator

    kw = dict(
        ticks=500, cdc_fanout=3, ingress_gateway=True, storm_clients=5
    )
    a = Simulator(211, **kw)
    sa = a.run()
    assert sa["cdc_fanout_consumers"] == 3
    assert sa["cdc_fanout_refusals"] > 0
    lag = sa["cdc_fanout_lag_max"]
    assert lag["slow"] >= max(v for k, v in lag.items() if k != "slow")
    b = Simulator(211, **kw)
    sb = b.run()
    assert sa == sb
    for name in a.cdc_fanout.stores:
        assert (
            a.cdc_fanout.stores[name].stream
            == b.cdc_fanout.stores[name].stream
        ), name


@pytest.mark.slow
def test_simulator_ingress_more_seeds():
    from tigerbeetle_tpu.testing.simulator import run_simulation

    for seed in (7, 23, 31, 59):
        stats = run_simulation(
            seed, ticks=800, cdc_fanout=3, ingress_gateway=True,
            storm_clients=4 + seed % 8,
        )
        assert stats["committed_ops"] > 0


# ---------------------------------------------------------------------
# the front door end-to-end (multiplexed driver against a real server)
# ---------------------------------------------------------------------


def test_ingress_sessions_smoke_500():
    """Tier-1 smoke: 500 live multiplexed sessions over 8 connections
    through the gateway — registration storm, live p99 vs baseline,
    saturation sheds, conservation verified over the wire (inside the
    driver)."""
    from tigerbeetle_tpu.benchmark import run_ingress_sessions

    out = run_ingress_sessions(
        n_sessions=500, conns=8, n_accounts=64, baseline_sessions=4,
        driver_batches=3, batch=64, bg_window=8, sat_window=64,
        sat_batches=16, reg_window=128,
    )
    assert out["sessions"] == 500
    assert out["ingress_sessions_gauge"] == 500
    assert out["p99_ratio"] is not None
    # the registration storm + saturation phase exercised the shed path
    assert out["ingress_shed"] + out["busy_replies"] > 0
    assert out["ingress_admitted"] > 500  # registers + workload


@pytest.mark.slow
def test_ingress_sessions_soak_10k():
    """Nightly soak: >= 10k live sessions. The bench artifact evaluates
    the p99 <= 2x acceptance number; here we assert the structural
    contract with sandbox-tolerant bounds (sessions sustained, sheds
    typed and counted, saturated throughput does not collapse)."""
    from tigerbeetle_tpu.benchmark import run_ingress_sessions

    out = run_ingress_sessions(
        n_sessions=10_000, conns=16, n_accounts=256, baseline_sessions=10,
        driver_batches=10, batch=256, bg_window=32, sat_window=256,
        sat_batches=60, reg_window=512,
    )
    assert out["sessions"] == 10_000
    assert out["ingress_sessions_gauge"] == 10_000
    assert out["ingress_shed"] + out["busy_replies"] > 0
    assert out["tps_saturated_ratio"] and out["tps_saturated_ratio"] >= 0.7
    assert out["p99_ratio"] and out["p99_ratio"] <= 4.0
