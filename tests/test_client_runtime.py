"""The fault-tolerant client runtime (vsr/client.py tick state machine):
typed errors from the wait path, strict stale-busy handling, the
timeout -> re-target -> duplicate-reply-dedup ladder, busy backoff
distinct from loss backoff, ping/pong view discovery, per-request
deadlines, eviction -> automatic re-registration — each scripted
deterministically over the in-process cluster, then the whole state
machine under the seeded simulator's fault matrix with byte-identical
histories per seed."""

import pytest

from tigerbeetle_tpu.constants import ConfigCluster
from tigerbeetle_tpu.io.network import LinkControl
from tigerbeetle_tpu.metrics import Metrics
from tigerbeetle_tpu.models.oracle import OracleStateMachine
from tigerbeetle_tpu.testing.cluster import Cluster as _Cluster
from tigerbeetle_tpu.types import Operation
from tigerbeetle_tpu.vsr.client import (
    Client,
    RequestTimeout,
    SessionEvicted,
    Timeout,
    WallTicker,
)
from tigerbeetle_tpu.vsr.header import Command, Header

CID = (1 << 64) + 77


def Cluster(**kw):
    # oracle backend throughout: these tests exercise CLIENT behavior —
    # keeping the device ledger out makes them fast and keeps this
    # sandbox's documented XLA-CPU/native fragility (CHANGES.md) away
    # from extra in-process device work
    return _Cluster(backend_factory=OracleStateMachine, **kw)


def _accounts_batch(seed: int = 5, n: int = 4) -> bytes:
    # explicit valid accounts in a per-`seed` id range (the workload
    # GENERATOR deliberately mixes invalid events — wrong tool here)
    from tigerbeetle_tpu.benchmark import _accounts_body

    return _accounts_body(1 + seed * 1000, n)


def _register(cluster: Cluster, client: Client) -> None:
    client.register()
    cluster.network.run()
    client.take_reply()
    assert client.session != 0


# ----------------------------------------------------------------------
# satellite: eviction surfaces as a typed error from the wait path
# ----------------------------------------------------------------------


def test_eviction_raises_session_evicted_from_wait_path():
    """Regression (the old behavior): eviction set a silent flag and the
    in-flight request vanished — wait loops spun forever. Now the wait
    path (poll / take_reply) raises the typed SessionEvicted naming the
    dropped request."""
    small = ConfigCluster(
        journal_slot_count=64, lsm_batch_multiple=4, clients_max=2,
    )
    cluster = Cluster(replica_count=3, cluster=small)
    c0 = cluster.add_client()
    # put a request IN FLIGHT on c0, with its delivery held so the
    # eviction (caused by register pressure) lands first
    lc = LinkControl(cluster.network)
    hold = lc.hold(src=c0.client_id)
    c0.request(Operation.create_accounts, _accounts_batch())
    assert c0.in_flight is not None
    cluster.add_client()
    cluster.add_client()  # clients_max=2: evicts c0 (oldest session)
    assert c0.evicted
    assert c0.in_flight is None  # dropped, not silently retried
    with pytest.raises(SessionEvicted) as err:
        c0.take_reply()  # the wait path surfaces it
    assert err.value.request == 1
    # the error is consumed by raising: a second poll is clean
    c0.poll()
    del hold
    lc.clear()


def test_eviction_while_idle_surfaces_once():
    small = ConfigCluster(
        journal_slot_count=64, lsm_batch_multiple=4, clients_max=2,
    )
    cluster = Cluster(replica_count=3, cluster=small)
    c0 = cluster.add_client()
    cluster.add_client()
    cluster.add_client()
    assert c0.evicted
    with pytest.raises(SessionEvicted) as err:
        c0.poll()
    assert err.value.request is None  # idle: no request was harmed


# ----------------------------------------------------------------------
# satellite: stale busy strictly ignored
# ----------------------------------------------------------------------


def test_stale_busy_strictly_ignored():
    """A busy reply for anything but the CURRENT in-flight request
    (matched by request number AND operation) must change nothing: no
    counter, no flag, no backoff scheduling."""
    cluster = Cluster(replica_count=1)
    m = Metrics()
    c = Client(CID, cluster.network, 1, metrics=m)
    _register(cluster, c)

    def busy(request: int, operation: int) -> None:
        h = Header(
            command=int(Command.busy), client=CID,
            request=request, operation=operation, replica=0,
        )
        h.set_checksum_body(b"")
        h.set_checksum()
        c._on_message(0, h.to_bytes())

    c.request(Operation.create_accounts, _accounts_batch())
    # wrong request number; right number but wrong operation; and one
    # with nothing in flight below — all strictly ignored
    busy(c.request_number + 1, int(Operation.create_accounts))
    busy(c.request_number, int(Operation.create_transfers))
    assert c.busy_replies == 0 and not c.busy
    assert m.counter("client.busy_sheds").value == 0
    # the real one counts exactly once
    busy(c.request_number, int(Operation.create_accounts))
    assert c.busy_replies == 1 and c.busy
    cluster.network.run()
    c.take_reply()
    # late duplicate busy after the reply: in_flight is None -> ignored
    busy(c.request_number, int(Operation.create_accounts))
    assert c.busy_replies == 1 and not c.busy
    assert m.counter("client.busy_sheds").value == 1


# ----------------------------------------------------------------------
# timeout -> re-target -> duplicate-reply dedup
# ----------------------------------------------------------------------


def test_timeout_retargets_round_robin_and_dedups_duplicate_replies():
    cluster = Cluster(replica_count=3)
    m = Metrics()
    c = Client(CID, cluster.network, 3, metrics=m,
               request_timeout_ticks=4, max_backoff_exponent=1)
    _register(cluster, c)
    lc = LinkControl(cluster.network)
    lc.hold(src=CID, dst=0, count=1)  # the first send is captured
    body = _accounts_batch()
    c.request(Operation.create_accounts, body)
    commit_before = cluster.replicas[0].commit_min
    # tick until the retry ladder walks the cluster back to the primary:
    # fire 1 -> replica 1 (dropped: not primary), fire 2 -> replica 2
    # (dropped), fire 3 -> replica 0 (served)
    for _ in range(80):
        c.tick()
        cluster.network.run()
        if c.reply is not None:
            break
    assert c.reply is not None
    assert m.counter("client.timeouts").value >= 3
    assert m.counter("client.retargets").value >= 2
    # the HELD original now arrives twice (delayed + duplicated): the
    # replica dedups via its client table and resends the cached reply;
    # the client ignores both as stale
    c.take_reply()
    lc.clear()
    lc.release(duplicate=2)
    cluster.network.run()
    assert cluster.replicas[0].commit_min == commit_before + 1
    assert c.reply is None  # nothing awaited: duplicates dropped
    assert m.counter("client.stale_replies").value >= 1


# ----------------------------------------------------------------------
# busy backoff: distinct ladder, runtime-driven resend
# ----------------------------------------------------------------------


def test_busy_backoff_resends_without_driver_and_loss_ladder_stays_cold():
    from tigerbeetle_tpu.ingress import IngressGateway

    cluster = Cluster(replica_count=1)
    m = Metrics()
    r = cluster.replicas[0]
    gw = IngressGateway(cluster.network, r)
    gw.install()
    c = Client(CID, cluster.network, 1, metrics=m,
               request_timeout_ticks=50)
    _register(cluster, c)

    orig = r.ingress_occupancy
    r.ingress_occupancy = lambda: (99, 8)  # saturated: shed everything
    gw.regulator.drain()
    c.request(Operation.create_accounts, _accounts_batch())
    cluster.network.run()
    assert c.busy and c.busy_replies == 1
    # a few sustained shed rounds: each runtime resend is answered busy
    for _ in range(30):
        c.tick()
        cluster.network.run()
    assert c.busy_replies >= 2  # the runtime resent into the shed wall
    # capacity returns: the next runtime resend is admitted and commits
    r.ingress_occupancy = orig
    gw.regulator.drain()
    for _ in range(80):
        c.tick()
        cluster.network.run()
        if c.reply is not None:
            break
    _h, body = c.take_reply()
    assert body == b""
    # DISTINCT ladders: every retry rode the busy (decorrelated) path;
    # the loss timeout never fired on top of it
    assert m.counter("client.busy_sheds").value == c.busy_replies
    assert m.counter("client.timeouts").value == 0
    gw.uninstall()


# ----------------------------------------------------------------------
# ping/pong view discovery while idle
# ----------------------------------------------------------------------


def test_idle_ping_discovers_view_change():
    cluster = Cluster(replica_count=3)
    m = Metrics()
    c = Client(CID, cluster.network, 3, metrics=m, ping_ticks=5)
    _register(cluster, c)
    assert c.view == 0 and c.primary_index == 0
    # primary crashes; the backups elect view 1 while the client idles
    cluster.detach_replica(0)
    cluster.run_ticks(120)
    assert cluster.replicas[1].status == "normal"
    new_view = cluster.replicas[1].view
    assert new_view > 0
    for _ in range(30):
        c.tick()
        cluster.network.run()
        if c.view == new_view:
            break
    assert c.view == new_view  # learned from pong_client, no request sent
    assert c.primary_index == new_view % 3
    assert m.counter("client.pings").value >= 1
    assert m.counter("client.pongs").value >= 1


# ----------------------------------------------------------------------
# per-request deadline -> typed RequestTimeout
# ----------------------------------------------------------------------


def test_deadline_surfaces_request_timeout_and_session_survives():
    cluster = Cluster(replica_count=1)
    m = Metrics()
    c = Client(CID, cluster.network, 1, metrics=m,
               request_timeout_ticks=3, deadline_ticks=10)
    _register(cluster, c)
    lc = LinkControl(cluster.network)
    lc.drop(src=CID, dst=0)  # blackhole: every send and retry lost
    c.request(Operation.create_accounts, _accounts_batch())
    for _ in range(12):
        c.tick()
    with pytest.raises(RequestTimeout) as err:
        c.poll()
    assert err.value.request == 1
    assert c.in_flight is None
    assert m.counter("client.deadline_timeouts").value == 1
    # the session is still usable once the fault heals
    lc.clear()
    c.request(Operation.create_accounts, _accounts_batch(seed=9))
    cluster.network.run()
    _h, body = c.take_reply()
    assert body == b""


# ----------------------------------------------------------------------
# eviction -> automatic re-registration
# ----------------------------------------------------------------------


def test_evicted_client_auto_reregisters_and_resumes():
    small = ConfigCluster(
        journal_slot_count=64, lsm_batch_multiple=4, clients_max=2,
    )
    cluster = Cluster(replica_count=3, cluster=small)
    m = Metrics()
    c0 = Client(CID, cluster.network, 3, metrics=m, auto_reregister=True)
    _register(cluster, c0)
    old_session = c0.session
    cluster.add_client()
    cluster.add_client()  # evicts c0
    assert c0.evicted
    # idle eviction + auto re-register: no error surfaces, the next
    # tick re-registers a FRESH session
    for _ in range(10):
        c0.tick()
        cluster.network.run()
        if c0.reply is not None:
            c0.take_reply()
        if c0.session != 0 and not c0.evicted:
            break
    assert c0.session != 0 and c0.session != old_session
    assert m.counter("client.reregisters").value == 1
    # ...and the session serves requests again
    c0.request(Operation.create_accounts, _accounts_batch(seed=11))
    cluster.network.run()
    _h, body = c0.take_reply()
    assert body == b""


def test_timeout_jitter_is_deterministic_per_client():
    import random

    rng_a = random.Random(1234)
    rng_b = random.Random(1234)
    ta = Timeout(30, rng_a)
    tb = Timeout(30, rng_b)
    seq_a = []
    seq_b = []
    for t, seq in ((ta, seq_a), (tb, seq_b)):
        t.start()
        seq.append(t.duration)
        for _ in range(5):
            t.backoff()
            seq.append(t.duration)
    assert seq_a == seq_b
    assert seq_a[-1] <= 30 * 16 * 1.5 + 1  # capped ladder (+<=50% jitter)


def test_wall_ticker_bounds_post_stall_burst():
    class _N:
        def attach(self, *_a):
            pass

        def send(self, *_a):
            pass

    c = Client(3, _N(), 1)
    w = WallTicker(c, tick_s=0.01, max_burst=8)
    w.advance(0.0)
    w.advance(10.0)  # a 10s driver stall is NOT 1000 retries
    assert c.ticks == 8


# ----------------------------------------------------------------------
# the seeded simulator matrix: every transition under the fault mix,
# byte-identical per seed
# ----------------------------------------------------------------------


def _run_sim(seed: int, **kw):
    from tigerbeetle_tpu.testing.simulator import Simulator

    sim = Simulator(seed, **kw)
    out = sim.run()
    return out, sim.histories


MATRIX = {
    # SIGKILL-the-primary with requests in flight: timeout -> re-target
    # -> duplicate-reply dedup carries the clients through failover
    "primary_crash": dict(
        ticks=700, primary_crash_probability=0.004, n_clients=3,
    ),
    # client frames dropped AND duplicated at high rate (requests,
    # replies, busy, evictions all affected)
    "client_frame_chaos": dict(
        ticks=600,
        options_kw=dict(
            client_loss_probability=0.15, client_replay_probability=0.15,
        ),
    ),
    # clock-skewed timeout firing: per-client fast/slow runtime clocks
    "clock_skew": dict(ticks=600, client_tick_skew=True, n_clients=4),
    # sustained shed: every replica gateway-fronted, a register storm on
    # top, busy backoff carries the fleet through admission
    "busy_shed_storm": dict(
        ticks=700, ingress_gateway=True, storm_clients=12, n_clients=3,
    ),
    # eviction churn: a 2-session client table under 3 auto-re-
    # registering clients — evict -> re-register -> resume, forever
    "evict_reregister": dict(
        ticks=600, n_clients=3, client_auto_reregister=True,
        cluster=ConfigCluster(
            journal_slot_count=64, lsm_batch_multiple=4, clients_max=2,
        ),
    ),
    # per-request deadlines under loss: RequestTimeout surfaces, the
    # slot retries with fresh work, histories stay linear
    "deadlines": dict(
        ticks=600, client_deadline_ticks=300, n_clients=3,
    ),
}


@pytest.mark.parametrize("case", sorted(MATRIX))
def test_client_runtime_simulator_matrix(case):
    from tigerbeetle_tpu.testing.packet_simulator import (
        PacketSimulatorOptions,
    )

    kw = dict(MATRIX[case])
    opts_kw = kw.pop("options_kw", None)
    if opts_kw is not None:
        kw["options"] = PacketSimulatorOptions(
            packet_loss_probability=0.02,
            packet_replay_probability=0.02,
            partition_probability=0.005,
            **opts_kw,
        )
    seed = 1009
    a_out, a_hist = _run_sim(seed, **kw)
    if opts_kw is not None:
        kw["options"] = PacketSimulatorOptions(
            packet_loss_probability=0.02,
            packet_replay_probability=0.02,
            partition_probability=0.005,
            **opts_kw,
        )
    b_out, b_hist = _run_sim(seed, **kw)
    # byte-identical per seed: the whole committed history, not just
    # the summary (bodies included)
    assert a_hist == b_hist
    assert a_out == b_out
    assert a_out["committed_ops"] > 5
    # the case-specific transition actually fired
    if case == "primary_crash":
        assert a_out["primary_crashes"] >= 1
    if case == "evict_reregister":
        assert a_out["client_evictions"] >= 1
