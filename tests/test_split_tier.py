"""The middle tier: conflict-scheduled hazard batches (HazardTracker.plan
+ DeviceLedger._execute_waves) — wave-eligible lanes run vectorized in
dependency-ordered waves, only the residue the masked kernels cannot
express pays the serial scan, results bit-exact against the oracle."""

import pytest

from tigerbeetle_tpu.constants import TEST_PROCESS
from tigerbeetle_tpu.models.ledger import DeviceLedger, HazardTracker
from tigerbeetle_tpu.models.oracle import OracleStateMachine
from tigerbeetle_tpu.testing.workload import WorkloadGenerator
from tigerbeetle_tpu.types import (
    Account,
    Operation,
    Transfer,
    TransferFlags,
    transfers_to_np,
)


def _setup_pair():
    oracle = OracleStateMachine()
    dev = DeviceLedger(process=TEST_PROCESS, mode="auto")
    ts = 10_000
    accounts = [Account(id=i, ledger=1, code=1) for i in range(1, 21)]
    ts += len(accounts)
    assert oracle.execute_dense(Operation.create_accounts, ts, accounts) == \
        dev.execute_dense(Operation.create_accounts, ts, accounts)
    return oracle, dev, ts


def _check(oracle, dev, ts, transfers, expect_decision=None):
    if expect_decision is not None:
        probe = HazardTracker()
        probe.pending_accounts = dict(dev.hazards.pending_accounts)
        probe.limit_account_ids = set(dev.hazards.limit_account_ids)
        probe._limit_lo = dev.hazards._limit_lo.copy()
        decision, _ = probe.plan(transfers_to_np(transfers))
        assert decision == expect_decision, decision
    ts += len(transfers)
    dense_o = oracle.execute_dense(Operation.create_transfers, ts, transfers)
    dense_d = dev.execute_dense(Operation.create_transfers, ts, transfers)
    assert dense_d == dense_o, list(zip(dense_d, dense_o))
    accounts_d, transfers_d, posted_d = dev.extract()
    assert accounts_d == oracle.accounts
    assert transfers_d == oracle.transfers
    assert posted_d == oracle.posted
    assert dev.commit_timestamp == oracle.commit_timestamp
    return ts


def test_split_mixed_two_phase_batch():
    """Interleaved simple transfers (disjoint accounts) + a pending/post
    pair: the simple majority must go FAST, the two-phase residue serial."""
    oracle, dev, ts = _setup_pair()
    # prior-batch pending on accounts 1,2
    ts = _check(oracle, dev, ts, [
        Transfer(id=100, debit_account_id=1, credit_account_id=2, amount=50,
                 ledger=1, code=1, flags=int(TransferFlags.pending)),
    ])
    transfers = []
    # 16 fast transfers over accounts 5..20 (disjoint from 1,2)
    for i in range(16):
        a = 5 + i % 8
        b = 13 + i % 8
        transfers.append(Transfer(id=200 + i, debit_account_id=a,
                                  credit_account_id=b, amount=1 + i,
                                  ledger=1, code=1))
    # the post of the pending is itself fast-eligible now (fast_pv)
    transfers.insert(7, Transfer(id=300, pending_id=100, amount=30,
                                 flags=int(TransferFlags.post_pending_transfer)))
    ts = _check(oracle, dev, ts, transfers, expect_decision="fast_pv")
    assert dev.hazards.split_stats.get("fast_pv", 0) >= 1

    # force a real SPLIT: add a linked chain on its own accounts
    transfers2 = [
        Transfer(id=310, debit_account_id=3, credit_account_id=4, amount=5,
                 ledger=1, code=1, flags=int(TransferFlags.linked)),
        Transfer(id=311, debit_account_id=3, credit_account_id=4, amount=6,
                 ledger=1, code=1),
    ] + [
        Transfer(id=320 + i, debit_account_id=5 + i % 8,
                 credit_account_id=13 + i % 8, amount=2 + i, ledger=1, code=1)
        for i in range(16)
    ]
    ts = _check(oracle, dev, ts, transfers2, expect_decision="waves")
    assert dev.hazards.split_stats["split"] >= 1


def test_split_moves_shared_account_events_to_residue():
    """A fast-looking event sharing an account with the residue must join
    the residue (fixpoint), or ordering would change its outcome."""
    oracle, dev, ts = _setup_pair()
    transfers = [
        # chain on accounts 1,2 that FAILS (rolls back)
        Transfer(id=400, debit_account_id=1, credit_account_id=2, amount=5,
                 ledger=1, code=1, flags=int(TransferFlags.linked)),
        Transfer(id=401, debit_account_id=1, credit_account_id=2, amount=0,
                 ledger=1, code=1),  # amount_must_not_be_zero -> chain fails
        # fast-looking event on account 2: MUST see the rollback
        Transfer(id=402, debit_account_id=2, credit_account_id=3, amount=7,
                 ledger=1, code=1),
    ] + [
        Transfer(id=500 + i, debit_account_id=5 + i, credit_account_id=6 + i,
                 amount=2, ledger=1, code=1)
        for i in range(0, 14, 2)
    ]
    ts = _check(oracle, dev, ts, transfers)


def test_split_balancing_residue():
    oracle, dev, ts = _setup_pair()
    transfers = [
        Transfer(id=600 + i, debit_account_id=5 + i, credit_account_id=6 + i,
                 amount=3, ledger=1, code=1)
        for i in range(0, 12, 2)
    ] + [
        # balancing on accounts 1,2 (disjoint): residue
        Transfer(id=700, debit_account_id=1, credit_account_id=2, amount=9,
                 ledger=1, code=1, flags=int(TransferFlags.balancing_debit)),
    ] + [
        Transfer(id=800 + i, debit_account_id=15 + (i % 4),
                 credit_account_id=19 - (i % 4) if 19 - (i % 4) != 15 + (i % 4)
                 else 12, amount=1, ledger=1, code=1)
        for i in range(8)
    ]
    ts = _check(oracle, dev, ts, transfers)


def test_plan_unknown_pending_ref_vs_order_sensitive_accounts():
    """A post referencing a pending the tracker never saw (e.g. created
    before a restart) has unprovable balance targets. With NO
    order-sensitive accounts (no balance limits, no balancing lanes) its
    effects commute, so it stays on the wave path — the kernel reads the
    pending's truth from the table. The moment order-sensitive accounts
    exist, it must join the serial residue."""
    transfers = [
        # a chain -> guarantees a residue exists
        Transfer(id=890, debit_account_id=30, credit_account_id=31, amount=1,
                 ledger=1, code=1, flags=int(TransferFlags.linked)),
        Transfer(id=891, debit_account_id=30, credit_account_id=31, amount=1,
                 ledger=1, code=1),
    ] + [
        Transfer(id=900 + i, debit_account_id=5 + i, credit_account_id=6 + i,
                 amount=2, ledger=1, code=1)
        for i in range(0, 18, 2)
    ] + [
        Transfer(id=950, pending_id=424242,  # a pending we never saw
                 flags=int(TransferFlags.post_pending_transfer)),
    ]
    arr = transfers_to_np(transfers)
    tracker = HazardTracker()
    decision, plan = tracker.plan(arr)
    assert decision == "waves"
    assert plan.wave_of[0] < 0 and plan.wave_of[1] < 0  # the chain
    assert plan.wave_of[-1] >= 0  # commuting effects: stays on a wave

    limited = HazardTracker()
    limited.limit_account_ids = {77}
    import numpy as np
    limited._limit_lo = np.array([77], dtype=np.uint64)
    decision2, plan2 = limited.plan(arr)
    assert decision2 == "waves"
    assert plan2.wave_of[-1] < 0  # unprovable targets join the residue


def test_fast_pv_pure_post_batch():
    """A whole batch of posts/voids of distinct prior pendings runs the
    VECTORIZED fast_pv tier (no serial scan), bit-exact against the oracle."""
    oracle, dev, ts = _setup_pair()
    # 12 pendings in one (fast) batch
    pends = [
        Transfer(id=1000 + i, debit_account_id=1 + i % 10,
                 credit_account_id=11 + i % 10, amount=100 + i, ledger=1,
                 code=1, flags=int(TransferFlags.pending))
        for i in range(12)
    ]
    ts = _check(oracle, dev, ts, pends, expect_decision="fast")
    # posts (partial amounts), voids, one bad reference, one expired-free mix
    resolves = [
        Transfer(id=2000 + i, pending_id=1000 + i, amount=50 + i,
                 flags=int(TransferFlags.post_pending_transfer))
        for i in range(6)
    ] + [
        Transfer(id=2100 + i, pending_id=1006 + i,
                 flags=int(TransferFlags.void_pending_transfer))
        for i in range(4)
    ] + [
        Transfer(id=2200, pending_id=999999,  # not found
                 flags=int(TransferFlags.post_pending_transfer)),
        Transfer(id=2201, pending_id=0,  # must_not_be_zero
                 flags=int(TransferFlags.void_pending_transfer)),
    ]
    ts = _check(oracle, dev, ts, resolves, expect_decision="fast_pv")
    assert dev.hazards.split_stats.get("fast_pv", 0) >= 1
    # double-resolve attempts (already posted/voided) go serial (dup refs
    # would be order-dependent) — still exact
    again = [
        Transfer(id=2300, pending_id=1000, amount=10,
                 flags=int(TransferFlags.post_pending_transfer)),
        Transfer(id=2301, pending_id=1000, amount=10,
                 flags=int(TransferFlags.post_pending_transfer)),
    ]
    ts = _check(oracle, dev, ts, again)


def test_fast_pv_mixed_with_simple_shared_accounts():
    """fast_pv with posts and simple transfers hitting the SAME accounts in
    one batch: the signed accumulator must net them exactly."""
    oracle, dev, ts = _setup_pair()
    pends = [
        Transfer(id=3000 + i, debit_account_id=1, credit_account_id=2,
                 amount=40 + i, ledger=1, code=1,
                 flags=int(TransferFlags.pending))
        for i in range(4)
    ]
    ts = _check(oracle, dev, ts, pends)
    mixed = [
        Transfer(id=3100, pending_id=3000, amount=15,
                 flags=int(TransferFlags.post_pending_transfer)),
        Transfer(id=3101, debit_account_id=1, credit_account_id=2, amount=7,
                 ledger=1, code=1),
        Transfer(id=3102, pending_id=3001,
                 flags=int(TransferFlags.void_pending_transfer)),
        Transfer(id=3103, debit_account_id=2, credit_account_id=1, amount=3,
                 ledger=1, code=1),
        Transfer(id=3104, pending_id=3002, amount=42,
                 flags=int(TransferFlags.post_pending_transfer)),
    ]
    ts = _check(oracle, dev, ts, mixed, expect_decision="fast_pv")


@pytest.mark.parametrize("seed", [21, 22])
def test_split_randomized_parity(seed):
    """Randomized mixed-hazard workload through auto dispatch: the split
    engages and parity stays bit-exact."""
    oracle = OracleStateMachine()
    dev = DeviceLedger(process=TEST_PROCESS, mode="auto")
    gen = WorkloadGenerator(
        seed, chain_rate=0.03, two_phase_rate=0.08, balancing_rate=0.03,
        limit_account_rate=0.05, conflict_rate=0.08, invalid_rate=0.1,
    )
    ts = 1_000_000_000
    for b in range(8):
        if b % 4 == 0:
            op, events = gen.gen_accounts_batch(48)
        else:
            op, events = gen.gen_transfers_batch(48)
        ts += len(events)
        dense_o = oracle.execute_dense(op, ts, events)
        dense_d = dev.execute_dense(op, ts, events)
        assert dense_d == dense_o, f"batch {b}"
    accounts_d, transfers_d, posted_d = dev.extract()
    assert accounts_d == oracle.accounts
    assert transfers_d == oracle.transfers
    assert posted_d == oracle.posted
