"""Dual-commit verification seam: the native C++ engine and the JAX
DeviceLedger must agree on a single order-independent state fingerprint and
on a chained digest of the dense reply-code stream.

This is the machinery behind `--backend native+device` (the dual durable
server): the native engine serves replies at host speed while the device
applies the SAME prepares asynchronously (h2d only); at shutdown one
scalar fetch proves the device state bit-identical (reference seam:
src/state_machine.zig:508-540 — determinism is the consensus invariant,
extended here across heterogeneous engines).
"""

import numpy as np
import pytest

from tigerbeetle_tpu import types
from tigerbeetle_tpu.constants import ConfigProcess
from tigerbeetle_tpu.models.ledger import (
    DeviceLedger,
    fold_reply_codes,
    fold_reply_codes_np,
)
from tigerbeetle_tpu.models.native_ledger import NativeLedger
from tigerbeetle_tpu.testing.workload import WorkloadGenerator
from tigerbeetle_tpu.types import Operation


def _run_pair(seed: int, n_batches: int = 10, batch: int = 64):
    """Drive the same random workload through both engines; return
    (native, device, native_fold, device_fold_scalar)."""
    import jax
    import jax.numpy as jnp

    gen = WorkloadGenerator(seed)
    nat = NativeLedger(12, 14)
    dev = DeviceLedger(
        process=ConfigProcess(account_slots_log2=12, transfer_slots_log2=14),
        mode="auto",
    )
    fold = jax.jit(fold_reply_codes)
    chk_dev = jnp.uint64(0)
    chk_nat = 0
    for b in range(n_batches):
        if b % 3 == 0:
            op, events = gen.gen_accounts_batch(batch)
        else:
            op, events = gen.gen_transfers_batch(batch)
        nat.prepare(op, len(events))
        dev.prepare(op, len(events))
        assert nat.prepare_timestamp == dev.prepare_timestamp
        ts = nat.prepare_timestamp
        arr = (
            types.accounts_to_np(events)
            if op == Operation.create_accounts
            else types.transfers_to_np(events)
        )
        pn = nat.execute_async(op, ts, arr)
        pd = dev.execute_async(op, ts, arr)
        chk_dev = fold(chk_dev, pd.results, jnp.int32(len(events)))
        pn.wait()
        chk_nat = fold_reply_codes_np(chk_nat, pn.codes)
        # codes agree batch-by-batch too (the stronger per-batch check —
        # the fold is what the production server uses because it needs
        # no d2h until shutdown)
        assert nat.drain(pn) == dev.drain(pd), f"seed {seed} batch {b}"
    dev.check_fault()
    return nat, dev, chk_nat, int(np.asarray(chk_dev))


@pytest.mark.parametrize("seed", [3, 17, 44])
def test_fingerprint_and_code_fold_parity(seed):
    nat, dev, chk_nat, chk_dev = _run_pair(seed)
    assert chk_nat == chk_dev, "reply-code stream digests diverged"
    fn = nat.fingerprint()
    fd = dev.fingerprint()
    assert fn["accounts"] == fd["accounts"]
    assert fn["transfers"] == fd["transfers"]
    assert fn["accounts_fp"] == fd["accounts_fp"], "account state diverged"
    assert fn["transfers_fp"] == fd["transfers_fp"], "transfer state diverged"
    assert fn["commit_timestamp"] == fd["commit_timestamp"]


def test_fingerprint_detects_divergence():
    """One flipped balance on one engine must flip the fingerprint (the
    check is only as good as its sensitivity)."""
    nat, dev, _, _ = _run_pair(3, n_batches=4)
    # two fresh accounts + one transfer applied to the NATIVE engine only
    accts = [
        types.Account(id=77_000_001, ledger=1, code=1),
        types.Account(id=77_000_002, ledger=1, code=1),
    ]
    nat.prepare(Operation.create_accounts, 2)
    assert nat.execute_dense(
        Operation.create_accounts, nat.prepare_timestamp, accts
    ) == [0, 0]
    fp_before = nat.fingerprint()["accounts_fp"]
    t = types.Transfer(
        id=77_000_003, debit_account_id=77_000_001,
        credit_account_id=77_000_002, amount=1, ledger=1, code=1,
    )
    nat.prepare(Operation.create_transfers, 1)
    assert nat.execute_dense(
        Operation.create_transfers, nat.prepare_timestamp, [t]
    ) == [0]
    assert nat.fingerprint()["accounts_fp"] != fp_before


def test_code_fold_order_sensitivity():
    """The chained fold must distinguish permuted batch orders and permuted
    lanes (hash_log semantics: the STREAM is the contract)."""
    a = np.array([0, 0, 5, 0], dtype=np.uint32)
    b = np.array([0, 7, 0, 0], dtype=np.uint32)
    ab = fold_reply_codes_np(fold_reply_codes_np(0, a), b)
    ba = fold_reply_codes_np(fold_reply_codes_np(0, b), a)
    assert ab != ba
    perm = fold_reply_codes_np(0, a[::-1].copy())
    assert perm != fold_reply_codes_np(0, a)


def test_dual_server_end_to_end_verifies_shadow():
    """Real `--backend native+device` server process: native replies over
    TCP while the device shadows; SIGTERM must report verified=true with
    matching digests, and the group-commit path must have fused (the
    native engine's try_execute_group_async)."""
    from tigerbeetle_tpu.benchmark import run_e2e

    out = run_e2e(
        n_accounts=200,
        n_transfers=64 * 8,
        batch=64,
        clients=4,
        warmup_batches=1,
        jax_platform="cpu",
        backend="native+device",
    )
    shadow = out.get("device_shadow")
    assert shadow is not None, out.get("server_stats")
    assert shadow["verified"] is True, shadow
    assert shadow["shadow_batches"] >= 9  # accounts + warmup + timed
    d = shadow["code_stream_digest"]
    assert d["native"] == d["device"]
    assert out["durable_tps"] > 0


# ---------------------------------------------------------------------
# dual-commit FOLLOWER mode (`--backend dual`): the replica enqueues
# committed ops at finalize; per-op hash-log rings localize divergence;
# checkpoint/restart recovers device parity via snapshot row install;
# bounded-lag backpressure throttles admission through the regulator.
# ---------------------------------------------------------------------


def _valid_accounts(start: int, n: int) -> np.ndarray:
    a = np.zeros(n, dtype=types.ACCOUNT_DTYPE)
    a["id_lo"] = np.arange(start, start + n, dtype=np.uint64)
    a["ledger"] = 1
    a["code"] = 1
    return a


def _valid_transfers(start: int, n: int, flags: int = 0,
                     pend_ids=None) -> np.ndarray:
    x = np.zeros(n, dtype=types.TRANSFER_DTYPE)
    x["id_lo"] = np.arange(start, start + n, dtype=np.uint64)
    x["debit_account_id_lo"] = 1 + np.arange(n) % 9
    x["credit_account_id_lo"] = 1 + (np.arange(n) + 1) % 9
    x["amount_lo"] = 1
    x["ledger"] = 1
    x["code"] = 1
    x["flags"] = flags
    if pend_ids is not None:
        x["pending_id_lo"] = pend_ids
        x["debit_account_id_lo"] = 0
        x["credit_account_id_lo"] = 0
        x["amount_lo"] = 0
    return x


def _drive_follower(led, op, arr, op_no: int) -> None:
    """One committed op through the follower seam, the way the replica
    does it: native execute (reply path), then apply_commit at finalize
    with the native dense codes."""
    led.prepare(op, len(arr))
    ts = led.prepare_timestamp
    p = led.execute_async(op, ts, arr)
    led.drain(p)
    led.apply_commit(op_no, op, ts, arr, p.codes,
                     prepare_checksum=0xABCD_0000 + op_no)


def test_dual_follower_parity_mixed_workload_with_fused_runs():
    """(a) Bit-exact parity after a seeded mixed workload — accounts,
    simple transfers, two-phase pend->post — with FORCED fused apply runs
    (a brief applier stall queues consecutive create_transfers ops, so
    the loop coalesces them into group dispatches)."""
    from tigerbeetle_tpu.models.dual_ledger import DualLedger

    led = DualLedger(12, 14, follower=True)
    op_no = 0
    op_no += 1
    _drive_follower(led, Operation.create_accounts,
                    _valid_accounts(1, 16), op_no)
    # stall one apply turn: the ops below queue up behind it and the
    # loop MUST coalesce them into at least one fused group dispatch
    led._test_apply_delay_s = 0.3
    for g in range(5):
        op_no += 1
        _drive_follower(led, Operation.create_transfers,
                        _valid_transfers(1000 + 64 * g, 64), op_no)
    led._test_apply_delay_s = 0.0
    # drain before the two-phase ops: a pending-flagged batch in the
    # same apply stretch would (correctly) veto fusion for the run
    assert led.drain_applier(500)
    pend = _valid_transfers(5000, 32, flags=2)  # pending
    op_no += 1
    _drive_follower(led, Operation.create_transfers, pend, op_no)
    post = _valid_transfers(6000, 32, flags=4, pend_ids=pend["id_lo"])
    op_no += 1
    _drive_follower(led, Operation.create_transfers, post, op_no)
    # seeded generator tail: mixed valid/invalid events through the same
    # stream (codes on both sides must match failure for failure)
    gen = WorkloadGenerator(13)
    for b in range(4):
        op, events = (
            gen.gen_accounts_batch(32) if b % 2 == 0
            else gen.gen_transfers_batch(32)
        )
        arr = (
            types.accounts_to_np(events)
            if op == Operation.create_accounts
            else types.transfers_to_np(events)
        )
        op_no += 1
        _drive_follower(led, op, arr, op_no)
    report = led.finalize(timeout=500)
    assert report["verified"] is True, report
    assert report["shadow_batches"] == op_no
    assert report["hash_log"]["ok"] is True
    assert report["hash_log"]["ops"] == op_no
    assert report["hash_log"]["first_divergent_op"] is None
    assert report["shadow"]["groups"] >= 1, (
        "forced fused apply runs never coalesced", report["shadow"]
    )


def test_dual_follower_hash_log_names_first_divergent_op():
    """(c) A deliberate fault injected into the device applier at op K
    fails the end-of-run check AT exactly op K (hash-log check-mode
    semantics: the ring names the op, not just 'digests differ')."""
    from tigerbeetle_tpu.models.dual_ledger import (
        DualLedger,
        raise_on_parity_divergence,
    )
    from tigerbeetle_tpu.testing.hash_log import HashLogDivergence

    led = DualLedger(12, 14, follower=True)
    led._test_corrupt_apply_op = 4
    op_no = 0
    op_no += 1
    _drive_follower(led, Operation.create_accounts,
                    _valid_accounts(1, 16), op_no)
    for g in range(6):
        op_no += 1
        _drive_follower(led, Operation.create_transfers,
                        _valid_transfers(1000 + 32 * g, 32), op_no)
    report = led.finalize(timeout=500)
    assert report["verified"] is False
    assert report["hash_log"]["ok"] is False
    assert report["hash_log"]["first_divergent_op"] == 4, report["hash_log"]
    # the divergent op's PREPARE checksum ties back to the consensus
    # stream (the hash_log recording / WAL carry the same value)
    assert report["hash_log"]["prepare"] == hex(0xABCD_0000 + 4)
    with pytest.raises(HashLogDivergence) as exc:
        raise_on_parity_divergence(report)
    assert exc.value.op == 4
    assert exc.value.kind == "device-apply"


def test_dual_follower_checkpoint_restart_mid_lag():
    """(b) A checkpoint taken MID-APPLY-LAG drains the applier first;
    a crash-restart over the surviving storage re-seeds the device from
    the native snapshot (row install, h2d only), replays the WAL tail
    through the apply seam, and ends bit-exact."""
    from tigerbeetle_tpu.models.dual_ledger import DualLedger
    from tigerbeetle_tpu.testing.cluster import Cluster

    cluster = Cluster(
        replica_count=1,
        backend_factory=lambda: DualLedger(12, 14, follower=True),
    )
    r = cluster.replicas[0]
    assert r._dual_apply
    c = cluster.add_client()
    _h, body = cluster.execute(
        c, Operation.create_accounts, _valid_accounts(1, 10).tobytes()
    )
    assert body == b""
    for g in range(3):
        _h, body = cluster.execute(
            c, Operation.create_transfers,
            _valid_transfers(100 + 32 * g, 32).tobytes(),
        )
        assert body == b""
    # build real lag, then checkpoint: the checkpoint must drain it
    r.ledger._test_apply_delay_s = 0.2
    for g in range(3):
        cluster.execute(
            c, Operation.create_transfers,
            _valid_transfers(500 + 32 * g, 32).tobytes(),
        )
    assert r.ledger.apply_lag_ops() > 0, "test never built apply lag"
    r.ledger._test_apply_delay_s = 0.0
    r.checkpoint()
    assert r.ledger.apply_lag_ops() == 0, (
        "checkpoint must drain the device applier"
    )
    # a post-checkpoint op leaves a WAL tail for restart to replay
    cluster.execute(
        c, Operation.create_transfers, _valid_transfers(700, 32).tobytes()
    )
    r2 = cluster.restart_replica(0)
    assert r2.commit_min > r2.checkpoint_op  # the tail replayed
    # the restarted replica's device follows again: new commits + parity
    c2 = cluster.add_client()
    # includes a post of a RESTORED pending (exercises the installed
    # fulfill column, not just row images)
    pend = _valid_transfers(800, 16, flags=2)
    _h, body = cluster.execute(
        c2, Operation.create_transfers, pend.tobytes()
    )
    assert body == b""
    _h, body = cluster.execute(
        c2, Operation.create_transfers,
        _valid_transfers(900, 16, flags=4,
                         pend_ids=pend["id_lo"]).tobytes(),
    )
    assert body == b""
    assert r2.ledger.drain_applier(500)
    report = r2.ledger.finalize(timeout=500)
    assert report["verified"] is True, report
    assert report["hash_log"]["ok"] is True


def test_dual_follower_backpressure_bounds_lag():
    """Sustained overload against a deliberately slow applier: the lag
    excess feeds ingress_occupancy, the PR-6 credit regulator sheds, and
    the lag stays bounded by window + pipeline cap instead of growing
    with offered load."""
    import time

    from tigerbeetle_tpu.ingress import CreditRegulator
    from tigerbeetle_tpu.models.dual_ledger import DualLedger
    from tigerbeetle_tpu.testing.cluster import Cluster

    cluster = Cluster(
        replica_count=1,
        backend_factory=lambda: DualLedger(
            12, 14, follower=True, lag_window=2
        ),
    )
    r = cluster.replicas[0]
    c = cluster.add_client()
    cluster.execute(
        c, Operation.create_accounts, _valid_accounts(1, 10).tobytes()
    )
    cluster.execute(
        c, Operation.create_transfers, _valid_transfers(100, 8).tobytes()
    )
    r.ledger._test_apply_delay_s = 0.25
    reg = CreditRegulator(r)
    _used, cap = r.ingress_occupancy()
    shed = admitted = 0
    max_lag = 0
    for g in range(12):
        if not reg.try_admit():
            shed += 1
            reg.drain()  # observe fresh occupancy next attempt
            time.sleep(0.02)
        else:
            cluster.execute(
                c, Operation.create_transfers,
                _valid_transfers(1000 + 8 * g, 8).tobytes(),
            )
            admitted += 1
        max_lag = max(max_lag, r.ledger.apply_lag_ops())
    assert shed > 0, "regulator never shed under applier overload"
    assert admitted > 0
    # bounded: lag never exceeds the window plus one pipeline cap of
    # already-admitted work
    assert max_lag <= r.ledger.lag_window + cap, (max_lag, cap)
    r.ledger._test_apply_delay_s = 0.0
    assert r.ledger.drain_applier(500)
    report = r.ledger.finalize(timeout=500)
    assert report["verified"] is True, report


def test_apply_lag_counts_items_not_op_distance():
    """Regression: lag is enqueued-minus-applied ITEMS (one per create
    op), not op-number distance — interleaved non-create ops and the
    post-restart op jump must not read as phantom lag and shed
    admission."""
    from tigerbeetle_tpu.models.dual_ledger import DualLedger

    led = DualLedger(12, 14, follower=True)
    led._test_apply_delay_s = 0.5  # hold the applier so lag is visible
    # a WAL-tail replay after restart starts at a large op number
    _drive_follower(led, Operation.create_accounts,
                    _valid_accounts(1, 8), 100_000)
    _drive_follower(led, Operation.create_transfers,
                    _valid_transfers(100, 8), 100_050)  # 49 lookups between
    assert led.apply_lag_ops() <= 2, led.apply_lag_ops()
    led._test_apply_delay_s = 0.0
    assert led.drain_applier(500)
    assert led.apply_lag_ops() == 0
    assert led.finalize(timeout=500)["verified"] is True


def test_group_ring_fold_dump_slot_no_collision():
    """Regression: inactive lanes of a partially-filled fused group are
    routed to the ring's DUMP slot. Scattering their stale read-back at a
    real slot instead would race an active op whose slot collides
    (op % APPLY_RING == 0 landed on slot 0 with inactive lanes' zero
    idxs) — duplicate-index .at[].set is order-undefined, so a correct
    run could report a fabricated first_divergent_op."""
    import jax
    import jax.numpy as jnp

    from tigerbeetle_tpu.models.dual_ledger import (
        APPLY_RING,
        _fold_group_ring_fn,
    )
    from tigerbeetle_tpu.models.ledger import fold_reply_codes

    k, n_pad = 4, 8
    codes = jnp.arange(k * n_pad + 1, dtype=jnp.uint32)
    ns = jnp.array([5, 0, 0, 0], dtype=jnp.int32)
    active = jnp.array([True, False, False, False])
    # op 4096 -> slot 0; inactive lanes -> the dump slot (APPLY_RING)
    idxs = jnp.array([0, APPLY_RING, APPLY_RING, APPLY_RING],
                     dtype=jnp.int32)
    ring = jnp.full(APPLY_RING + 1, 999, dtype=jnp.uint64)
    chk0 = jnp.uint64(7)
    expect = int(np.asarray(
        jax.jit(fold_reply_codes)(chk0, codes[:n_pad], ns[0])
    ))
    chk, ring2 = _fold_group_ring_fn(k, n_pad)(
        chk0, ring, idxs, codes, ns, active
    )
    assert int(np.asarray(chk)) == expect
    assert int(np.asarray(ring2)[0]) == expect, (
        "slot 0 lost the active op's chain value to an inactive lane"
    )


def test_fused_run_ring_slot_collision_last_wins():
    """Regression: two ACTIVE ops in ONE fused apply run whose op
    numbers are congruent mod APPLY_RING (>4096 non-create ops between
    two queued creates) must not race the device-ring scatter — the
    earlier op routes to the dump slot so both rings deterministically
    keep the LAST op per slot, and a correct run stays verified."""
    from tigerbeetle_tpu.models.dual_ledger import APPLY_RING, DualLedger

    led = DualLedger(12, 14, follower=True)
    _drive_follower(led, Operation.create_accounts,
                    _valid_accounts(1, 16), 1)
    assert led.drain_applier(500)
    # stall one apply turn so the two colliding transfers coalesce into
    # one fused run
    led._test_apply_delay_s = 0.3
    _drive_follower(led, Operation.create_transfers,
                    _valid_transfers(1000, 64), 10)
    _drive_follower(led, Operation.create_transfers,
                    _valid_transfers(2000, 64), 10 + APPLY_RING)
    led._test_apply_delay_s = 0.0
    report = led.finalize(timeout=500)
    assert report["verified"] is True, report
    assert report["hash_log"]["ok"] is True, report["hash_log"]
    # both sides kept ONE entry for the shared slot (the later op)
    assert report["hash_log"]["ops"] == 2  # accounts slot + shared slot


def test_dual_follower_install_resets_nonempty_device():
    """Regression: a state-sync-shaped restore installs a snapshot onto a
    device that ALREADY applied ops — the install must reset the device
    tables first or every already-present key claims a second slot and
    the fingerprints diverge forever."""
    from tigerbeetle_tpu.models.dual_ledger import DualLedger

    led_a = DualLedger(12, 14, follower=True)
    op_no = 0
    op_no += 1
    _drive_follower(led_a, Operation.create_accounts,
                    _valid_accounts(1, 10), op_no)
    op_no += 1
    _drive_follower(led_a, Operation.create_transfers,
                    _valid_transfers(100, 16), op_no)
    snap = led_a.snapshot_bytes()
    assert led_a.finalize(timeout=500)["verified"] is True

    # a second follower applies a DIFFERENT history, then adopts the
    # snapshot (the state-sync jump shape)
    led_b = DualLedger(12, 14, follower=True)
    op_no_b = 0
    op_no_b += 1
    _drive_follower(led_b, Operation.create_accounts,
                    _valid_accounts(1, 10), op_no_b)
    op_no_b += 1
    _drive_follower(led_b, Operation.create_transfers,
                    _valid_transfers(5000, 16), op_no_b)
    assert led_b.drain_applier(500)
    led_b.restore_bytes(snap)
    # post-jump traffic, including rows the PRE-jump history also held
    op_no_b += 1
    _drive_follower(led_b, Operation.create_transfers,
                    _valid_transfers(200, 16), op_no_b)
    report = led_b.finalize(timeout=500)
    assert report["verified"] is True, report
    assert report["hash_log"]["ok"] is True


def test_dual_server_end_to_end_commit_cycle():
    """CI smoke (satellite): one dual-mode commit cycle end-to-end under
    JAX_PLATFORMS=cpu — real `--backend dual` server process, TCP
    clients, fused group commits, SIGTERM parity report with the hash-log
    ring green."""
    from tigerbeetle_tpu.benchmark import run_e2e

    out = run_e2e(
        n_accounts=200,
        n_transfers=64 * 8,
        batch=64,
        clients=4,
        warmup_batches=1,
        jax_platform="cpu",
        backend="dual",
    )
    shadow = out.get("device_shadow")
    assert shadow is not None, out.get("server_stats")
    assert shadow["verified"] is True, shadow
    assert shadow["hash_log"]["ok"] is True, shadow
    assert shadow["hash_log"]["ops"] >= 9
    d = shadow["code_stream_digest"]
    assert d["native"] == d["device"]
    assert out["durable_tps"] > 0
    assert out.get("device_hash_log_ok") is True
    # the applier's gauges surfaced through the registry snapshot
    assert out.get("device_lag_ops") is not None


def test_native_group_execute_matches_serial():
    """try_execute_group_async == k sequential execute_async calls, code
    for code and fingerprint for fingerprint."""
    gen = WorkloadGenerator(9)
    _op, accts = gen.gen_accounts_batch(64)
    a = NativeLedger(12, 14)
    b = NativeLedger(12, 14)
    arr = types.accounts_to_np(accts)
    for led in (a, b):
        led.prepare(Operation.create_accounts, len(arr))
        led.execute_dense(Operation.create_accounts, led.prepare_timestamp, arr)

    items = []
    for _g in range(5):
        _o, events = gen.gen_transfers_batch(48)
        for led in (a, b):
            led.prepare(Operation.create_transfers, len(events))
        items.append((a.prepare_timestamp, types.transfers_to_np(events)))

    pendings = a.try_execute_group_async(items)
    assert pendings is not None and len(pendings) == 5
    serial = [
        b.execute_dense(Operation.create_transfers, ts, arr)
        for ts, arr in items
    ]
    for p, want in zip(pendings, serial):
        assert a.drain(p) == want
    assert a.fingerprint() == b.fingerprint()
    # single-item groups fall back
    assert a.try_execute_group_async(items[:1]) is None
