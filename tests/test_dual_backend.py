"""Dual-commit verification seam: the native C++ engine and the JAX
DeviceLedger must agree on a single order-independent state fingerprint and
on a chained digest of the dense reply-code stream.

This is the machinery behind `--backend native+device` (the dual durable
server): the native engine serves replies at host speed while the device
applies the SAME prepares asynchronously (h2d only); at shutdown one
scalar fetch proves the device state bit-identical (reference seam:
src/state_machine.zig:508-540 — determinism is the consensus invariant,
extended here across heterogeneous engines).
"""

import numpy as np
import pytest

from tigerbeetle_tpu import types
from tigerbeetle_tpu.constants import ConfigProcess
from tigerbeetle_tpu.models.ledger import (
    DeviceLedger,
    fold_reply_codes,
    fold_reply_codes_np,
)
from tigerbeetle_tpu.models.native_ledger import NativeLedger
from tigerbeetle_tpu.testing.workload import WorkloadGenerator
from tigerbeetle_tpu.types import Operation


def _run_pair(seed: int, n_batches: int = 10, batch: int = 64):
    """Drive the same random workload through both engines; return
    (native, device, native_fold, device_fold_scalar)."""
    import jax
    import jax.numpy as jnp

    gen = WorkloadGenerator(seed)
    nat = NativeLedger(12, 14)
    dev = DeviceLedger(
        process=ConfigProcess(account_slots_log2=12, transfer_slots_log2=14),
        mode="auto",
    )
    fold = jax.jit(fold_reply_codes)
    chk_dev = jnp.uint64(0)
    chk_nat = 0
    for b in range(n_batches):
        if b % 3 == 0:
            op, events = gen.gen_accounts_batch(batch)
        else:
            op, events = gen.gen_transfers_batch(batch)
        nat.prepare(op, len(events))
        dev.prepare(op, len(events))
        assert nat.prepare_timestamp == dev.prepare_timestamp
        ts = nat.prepare_timestamp
        arr = (
            types.accounts_to_np(events)
            if op == Operation.create_accounts
            else types.transfers_to_np(events)
        )
        pn = nat.execute_async(op, ts, arr)
        pd = dev.execute_async(op, ts, arr)
        chk_dev = fold(chk_dev, pd.results, jnp.int32(len(events)))
        pn.wait()
        chk_nat = fold_reply_codes_np(chk_nat, pn.codes)
        # codes agree batch-by-batch too (the stronger per-batch check —
        # the fold is what the production server uses because it needs
        # no d2h until shutdown)
        assert nat.drain(pn) == dev.drain(pd), f"seed {seed} batch {b}"
    dev.check_fault()
    return nat, dev, chk_nat, int(np.asarray(chk_dev))


@pytest.mark.parametrize("seed", [3, 17, 44])
def test_fingerprint_and_code_fold_parity(seed):
    nat, dev, chk_nat, chk_dev = _run_pair(seed)
    assert chk_nat == chk_dev, "reply-code stream digests diverged"
    fn = nat.fingerprint()
    fd = dev.fingerprint()
    assert fn["accounts"] == fd["accounts"]
    assert fn["transfers"] == fd["transfers"]
    assert fn["accounts_fp"] == fd["accounts_fp"], "account state diverged"
    assert fn["transfers_fp"] == fd["transfers_fp"], "transfer state diverged"
    assert fn["commit_timestamp"] == fd["commit_timestamp"]


def test_fingerprint_detects_divergence():
    """One flipped balance on one engine must flip the fingerprint (the
    check is only as good as its sensitivity)."""
    nat, dev, _, _ = _run_pair(3, n_batches=4)
    # two fresh accounts + one transfer applied to the NATIVE engine only
    accts = [
        types.Account(id=77_000_001, ledger=1, code=1),
        types.Account(id=77_000_002, ledger=1, code=1),
    ]
    nat.prepare(Operation.create_accounts, 2)
    assert nat.execute_dense(
        Operation.create_accounts, nat.prepare_timestamp, accts
    ) == [0, 0]
    fp_before = nat.fingerprint()["accounts_fp"]
    t = types.Transfer(
        id=77_000_003, debit_account_id=77_000_001,
        credit_account_id=77_000_002, amount=1, ledger=1, code=1,
    )
    nat.prepare(Operation.create_transfers, 1)
    assert nat.execute_dense(
        Operation.create_transfers, nat.prepare_timestamp, [t]
    ) == [0]
    assert nat.fingerprint()["accounts_fp"] != fp_before


def test_code_fold_order_sensitivity():
    """The chained fold must distinguish permuted batch orders and permuted
    lanes (hash_log semantics: the STREAM is the contract)."""
    a = np.array([0, 0, 5, 0], dtype=np.uint32)
    b = np.array([0, 7, 0, 0], dtype=np.uint32)
    ab = fold_reply_codes_np(fold_reply_codes_np(0, a), b)
    ba = fold_reply_codes_np(fold_reply_codes_np(0, b), a)
    assert ab != ba
    perm = fold_reply_codes_np(0, a[::-1].copy())
    assert perm != fold_reply_codes_np(0, a)


def test_dual_server_end_to_end_verifies_shadow():
    """Real `--backend native+device` server process: native replies over
    TCP while the device shadows; SIGTERM must report verified=true with
    matching digests, and the group-commit path must have fused (the
    native engine's try_execute_group_async)."""
    from tigerbeetle_tpu.benchmark import run_e2e

    out = run_e2e(
        n_accounts=200,
        n_transfers=64 * 8,
        batch=64,
        clients=4,
        warmup_batches=1,
        jax_platform="cpu",
        backend="native+device",
    )
    shadow = out.get("device_shadow")
    assert shadow is not None, out.get("server_stats")
    assert shadow["verified"] is True, shadow
    assert shadow["shadow_batches"] >= 9  # accounts + warmup + timed
    d = shadow["code_stream_digest"]
    assert d["native"] == d["device"]
    assert out["durable_tps"] > 0


def test_native_group_execute_matches_serial():
    """try_execute_group_async == k sequential execute_async calls, code
    for code and fingerprint for fingerprint."""
    gen = WorkloadGenerator(9)
    _op, accts = gen.gen_accounts_batch(64)
    a = NativeLedger(12, 14)
    b = NativeLedger(12, 14)
    arr = types.accounts_to_np(accts)
    for led in (a, b):
        led.prepare(Operation.create_accounts, len(arr))
        led.execute_dense(Operation.create_accounts, led.prepare_timestamp, arr)

    items = []
    for _g in range(5):
        _o, events = gen.gen_transfers_batch(48)
        for led in (a, b):
            led.prepare(Operation.create_transfers, len(events))
        items.append((a.prepare_timestamp, types.transfers_to_np(events)))

    pendings = a.try_execute_group_async(items)
    assert pendings is not None and len(pendings) == 5
    serial = [
        b.execute_dense(Operation.create_transfers, ts, arr)
        for ts, arr in items
    ]
    for p, want in zip(pendings, serial):
        assert a.drain(p) == want
    assert a.fingerprint() == b.fingerprint()
    # single-item groups fall back
    assert a.try_execute_group_async(items[:1]) is None
