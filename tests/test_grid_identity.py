"""Grid block IDENTITY verification (the registry): a block can carry a
valid self-checksum and still be the WRONG block for its address — a
diverged peer serving repair, a misdirected write. The registry (addr ->
expected payload checksum, persisted as a checkpoint block chain) is the
parent-hash the reference gets from block-tree references
(src/vsr/grid.zig block ids carry checksums)."""

import pytest

from tigerbeetle_tpu.constants import TEST_CLUSTER
from tigerbeetle_tpu.io.storage import MemoryStorage, Zone, ZoneLayout
from tigerbeetle_tpu.lsm.grid import (
    BLOCK_SIZE,
    Grid,
    GridBlockCorrupt,
)


def _grid(block_count=192):
    layout = ZoneLayout(TEST_CLUSTER, grid_size=64 * 1024 * 1024)
    storage = MemoryStorage(layout)
    return Grid(storage, offset=0, block_count=block_count,
                cache_blocks=32), storage


def test_wrong_content_read_detected():
    """Swapping two blocks' bytes on disk leaves both self-consistent;
    only the identity registry catches it."""
    g, storage = _grid()
    a = g.create_block(b"block A payload")
    b = g.create_block(b"block B payload")
    raw_a = g.read_block_raw(a)
    raw_b = g.read_block_raw(b)
    storage.write(Zone.grid, (a - 1) * BLOCK_SIZE, raw_b)
    storage.write(Zone.grid, (b - 1) * BLOCK_SIZE, raw_a)
    g.cache.clear()
    assert not g.verify_block(a)
    with pytest.raises(GridBlockCorrupt, match="identity"):
        g.read_block(a)


def test_wrong_content_repair_install_rejected():
    """install_block_raw must refuse valid-checksum bytes that are not
    THIS address's block (a diverged peer's repair reply)."""
    g, _ = _grid()
    a = g.create_block(b"the real block")
    g2, _ = _grid()
    other = g2.create_block(b"a different block")
    wrong_raw = g2.read_block_raw(other)
    assert not g.install_block_raw(a, wrong_raw)
    # the RIGHT bytes install fine after a fault
    right_raw = g.read_block_raw(a)
    assert g.install_block_raw(a, right_raw)


def test_install_at_unregistered_address_gains_identity():
    """A block healed at an address with NO registry entry (healed before
    its first checkpoint, or a legacy restore) must gain identity coverage
    at install — otherwise it stays self-checksum-only forever AND is
    silently excluded from every future encode_chk_registry."""
    g, _ = _grid()
    a = g.create_block(b"heal me")
    raw = g.read_block_raw(a)
    want_chk = g.block_chk[a]
    del g.block_chk[a]  # simulate an unregistered address
    assert g.install_block_raw(a, raw)
    assert g.block_chk.get(a) == want_chk, (
        "healed block must enter the identity registry"
    )
    # ... and persist into the next checkpoint's registry chain
    head = g.encode_chk_registry()
    g.encode_free_set()
    g2 = Grid(g.storage, offset=0, block_count=192, cache_blocks=32)
    g2.restore_chk_registry(head)
    assert g2.block_chk.get(a) == want_chk


def test_registry_chain_roundtrip():
    """encode_chk_registry -> restore_chk_registry reproduces the
    registry exactly (chain blocks included), across enough entries to
    span multiple chain blocks."""
    g, storage = _grid()
    addrs = [g.create_block(f"payload {i}".encode()) for i in range(40)]
    head = g.encode_chk_registry()
    g.encode_free_set()
    saved = dict(g.block_chk)
    assert head["addr"] != 0

    g2 = Grid(storage, offset=0, block_count=192, cache_blocks=32)
    g2.restore_chk_registry(head)
    assert g2.block_chk == saved
    for a in addrs:
        assert g2.verify_block(a)

    # a second checkpoint releases the first chain and stays consistent
    head2 = g.encode_chk_registry()
    g.encode_free_set()
    g3 = Grid(storage, offset=0, block_count=192, cache_blocks=32)
    g3.restore_chk_registry(head2)
    for a in addrs:
        assert a in g3.block_chk


def test_empty_registry_head_roundtrip():
    g, storage = _grid()
    head = g.encode_chk_registry()
    assert head["addr"] == 0
    g2 = Grid(storage, offset=0, block_count=192, cache_blocks=32)
    g2.restore_chk_registry(head)
    assert g2.block_chk == {}
    g2.restore_chk_registry(None)  # legacy checkpoint: no head at all
    assert g2.block_chk == {}


def test_release_drops_registry_entry_at_checkpoint():
    g, _ = _grid()
    a = g.create_block(b"short lived")
    g.release(a)
    assert a in g.block_chk  # staged: still live for the old checkpoint
    g.encode_free_set()
    assert a not in g.block_chk


def test_registry_excludes_staged_frees():
    """The persisted registry must NOT contain entries for blocks freed
    at the same checkpoint: a restarted replica would otherwise rebuild a
    BIGGER registry than a peer that never restarted, its next chain
    would lay out differently, and every later allocation would diverge
    (repair-by-address depends on identical layouts)."""
    g, storage = _grid()
    keep = g.create_block(b"keeper")
    dead = g.create_block(b"compacted away")
    g.release(dead)  # staged until the encode below
    head = g.encode_chk_registry()
    g.encode_free_set()
    live_registry = dict(g.block_chk)
    assert dead not in live_registry

    g2 = Grid(storage, offset=0, block_count=192, cache_blocks=32)
    g2.restore_chk_registry(head)
    assert g2.block_chk == live_registry
    assert keep in g2.block_chk


def test_corrupt_registry_chain_degrades_at_restore(capsys):
    """A latent sector error in the registry CHAIN at local startup
    restore must not make restart unrecoverable (no peer-repair path
    exists at restore time): restore degrades to an EMPTY registry with
    a warning — identity checks fall back to self-checksum only — and
    every data block stays readable. Blocks written after the degrade
    regain registry coverage (and persist into the next chain)."""
    g, storage = _grid()
    addrs = [g.create_block(f"payload {i}".encode()) for i in range(40)]
    head = g.encode_chk_registry()
    g.encode_free_set()

    # corrupt the chain HEAD block on disk
    storage.fault(Zone.grid, (int(head["addr"]) - 1) * BLOCK_SIZE + 40, 64)

    g2 = Grid(storage, offset=0, block_count=192, cache_blocks=32)
    g2.restore_chk_registry(head)  # degrades, must NOT raise
    assert g2.block_chk == {}
    err = capsys.readouterr().err
    assert "registry chain corrupt" in err
    # self-checksum verification still guards every data block read
    for a in addrs:
        assert g2.read_block(a).startswith(b"payload")
    # blocks written after the degrade regain identity coverage and
    # persist into the next checkpoint's chain
    g2.free_set = g.free_set  # adopt the allocation state (as restore does)
    fresh = g2.create_block(b"post-degrade payload")
    assert g2.block_chk.get(fresh) is not None
    head2 = g2.encode_chk_registry()
    g2.encode_free_set()
    g3 = Grid(storage, offset=0, block_count=192, cache_blocks=32)
    g3.restore_chk_registry(head2)
    assert fresh in g3.block_chk
