"""C-ABI call-sequence coverage for the FFI clients (VERDICT r3 item 7).

The Go (clients/go/tb_client.go), Node (clients/node/tb_client.js), and
Java (clients/java/TBClient.java) clients are thin wrappers over the
tb_client C ABI, but this image ships none of those toolchains — so this
test replays their EXACT call sequences (argument shapes, reply-capacity
math, empty-batch guard, deinit) via ctypes against a live server. A
C-ABI change that would break any of them breaks here, in every CI
environment.
"""

import ctypes
import os
import subprocess
import sys

import numpy as np
import pytest

from tests.test_process import REPO, _free_port, _spawn_server
from tigerbeetle_tpu import types

EVENT = 128
RESULT = 8
ID = 16


@pytest.fixture(scope="module")
def server(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("c_abi")
    path = str(tmp / "data.tigerbeetle")
    port = _free_port()
    env = dict(os.environ, PYTHONPATH=REPO)
    fmt = subprocess.run(
        [sys.executable, "-m", "tigerbeetle_tpu", "format",
         "--cluster", "0", "--replica", "0", "--replica-count", "1",
         "--grid-mb", "8", path],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=120,
    )
    assert fmt.returncode == 0, fmt.stderr
    proc = _spawn_server(path, port)
    yield port
    proc.kill()
    proc.wait()


def _init(port: int):
    from tigerbeetle_tpu.client_ffi import _TBClientHandle, _lib

    lib = _lib()
    handle = ctypes.POINTER(_TBClientHandle)()
    client_id = b"\x01" + os.urandom(15)
    # the exact tb_client_init signature both clients bind
    rc = lib.tb_client_init(
        ctypes.byref(handle), f"127.0.0.1:{port}".encode(), 0, 0, client_id
    )
    assert rc == 0, rc
    return lib, handle


def _request(lib, handle, op: int, body: bytes, reply_cap: int):
    # the Go/Node wrappers' guard: zero reply capacity -> no call at all
    if reply_cap == 0:
        return b""
    reply = ctypes.create_string_buffer(reply_cap)
    reply_len = ctypes.c_uint64()
    body_ptr = body if body else None
    rc = lib.tb_client_request(
        handle, op, body_ptr, len(body), reply, reply_cap,
        ctypes.byref(reply_len),
    )
    assert rc == 0, rc
    return reply.raw[: reply_len.value]


def test_abi_sequence_two_phase(server):
    """The Go sample's sequence (clients/go/sample/main.go) == the Node
    sample's (clients/node/sample/main.js) == the Java sample's
    (clients/java/sample/Sample.java): create accounts, pending, partial
    post, lookups, empty batch, exists code, deinit."""
    lib, handle = _init(server)
    try:
        acc = types.accounts_to_np([
            types.Account(id=1, ledger=1, code=1),
            types.Account(id=2, ledger=1, code=1),
        ]).tobytes()
        # reply_cap math both clients use: n * RESULT for creates
        assert _request(lib, handle, 128, acc, 2 * RESULT) == b""

        pend = types.transfers_to_np([
            types.Transfer(id=100, debit_account_id=1, credit_account_id=2,
                           amount=500, ledger=1, code=1,
                           flags=int(types.TransferFlags.pending),
                           timeout=3600),
        ]).tobytes()
        assert _request(lib, handle, 129, pend, RESULT) == b""
        post = types.transfers_to_np([
            types.Transfer(id=101, pending_id=100, amount=300, ledger=1,
                           code=1,
                           flags=int(types.TransferFlags.post_pending_transfer)),
        ]).tobytes()
        assert _request(lib, handle, 129, post, RESULT) == b""

        # lookups: n * EVENT reply capacity; missing ids skipped
        ids = np.zeros(6, dtype=np.uint64)
        ids[0], ids[2], ids[4] = 1, 2, 999
        reply = _request(lib, handle, 130, ids.tobytes(), 3 * EVENT)
        rows = np.frombuffer(reply, dtype=types.ACCOUNT_DTYPE)
        assert len(rows) == 2
        assert rows[0]["debits_posted_lo"] == 300
        assert rows[1]["credits_posted_lo"] == 300
        assert rows[0]["debits_pending_lo"] == 0

        ids2 = np.zeros(4, dtype=np.uint64)
        ids2[0], ids2[2] = 100, 101
        reply = _request(lib, handle, 131, ids2.tobytes(), 2 * EVENT)
        xf = np.frombuffer(reply, dtype=types.TRANSFER_DTYPE)
        assert len(xf) == 2 and xf[1]["amount_lo"] == 300

        # duplicate -> sparse exists result (the decode both clients do)
        reply = _request(lib, handle, 129, pend, RESULT)
        res = np.frombuffer(reply, dtype=types.CREATE_TRANSFERS_RESULT_DTYPE)
        assert len(res) == 1 and res[0]["index"] == 0
        assert res[0]["result"] == int(types.CreateTransferResult.exists)

        # empty batch: the wrappers return early (no ABI call) — and the
        # ABI itself also tolerates it
        assert _request(lib, handle, 128, b"", 0) == b""
    finally:
        lib.tb_client_deinit(handle)


def test_abi_reply_overflow_errno(server):
    """reply_cap too small must fail -ENOSPC (the wrappers surface it as
    an error, never a truncated reply)."""
    import errno

    lib, handle = _init(server)
    try:
        acc = types.accounts_to_np([
            types.Account(id=0, ledger=1, code=1),  # id_must_not_be_zero
        ]).tobytes()
        reply = ctypes.create_string_buffer(1)  # too small for one result
        reply_len = ctypes.c_uint64()
        rc = lib.tb_client_request(
            handle, 128, acc, len(acc), reply, 1, ctypes.byref(reply_len)
        )
        assert rc == -errno.ENOSPC, rc
    finally:
        lib.tb_client_deinit(handle)
