"""Cluster-causal op tracing: trace ids, Perfetto flow events, stitching.

The contract under test (tracer.py stitch/flow_events + the span tags
threaded through replica/journal/bus/cdc/dual_ledger):

- one client request's trace id (vsr/header.py trace_id, derived from
  client id + request checksum) tags every leg of the op — quorum wait,
  journal write, commit dispatch/finalize, CDC emit, device apply — on
  EVERY replica that executes it;
- stitching per-replica dumps yields ONE Perfetto file whose flow events
  (s/t/f) connect those legs across pids, with no dangling flow ids even
  when the span ring overwrote part of an op's history;
- the TCP bus tags its frame-parse (ingress) and flush (reply egress)
  spans with the same ids.
"""

import json
import os
import socket
import subprocess
import sys
import time

import numpy as np
import pytest

import tests.conftest  # noqa: F401 — CPU platform before jax init
from tigerbeetle_tpu import types
from tigerbeetle_tpu.tracer import JsonTracer, dump_stitched
from tigerbeetle_tpu.types import Operation
from tigerbeetle_tpu.vsr.header import Command, Header, trace_id

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _accounts(ids):
    acct = np.zeros(len(ids), dtype=types.ACCOUNT_DTYPE)
    acct["id_lo"] = ids
    acct["ledger"] = 1
    acct["code"] = 1
    return acct


def _transfer(tid, debit=1, credit=2):
    t = np.zeros(1, dtype=types.TRANSFER_DTYPE)
    t["id_lo"] = tid
    t["debit_account_id_lo"] = debit
    t["credit_account_id_lo"] = credit
    t["amount_lo"] = 1
    t["ledger"] = 1
    t["code"] = 1
    return t


def _flow_ids(events):
    return {e["id"] for e in events if e.get("ph") in ("s", "t", "f")}


def _assert_flows_well_formed(events):
    """Every flow id's legs are ordered s, t*, f — a lone start or a
    step without its start would render as a dangling arrow."""
    per_id: dict[str, list[str]] = {}
    for e in events:
        if e.get("ph") in ("s", "t", "f"):
            per_id.setdefault(e["id"], []).append(e["ph"])
    assert per_id, "no flow events generated"
    for fid, phs in per_id.items():
        assert phs[0] == "s" and phs[-1] == "f", (fid, phs)
        assert all(p == "t" for p in phs[1:-1]), (fid, phs)
        assert len(phs) >= 2, (fid, phs)


def test_trace_id_deterministic_and_derivable_from_every_leg():
    """The id assigned at ingress (request client+checksum) is exactly
    re-derivable from a prepare or reply header's (client, context) —
    the propagation contract that lets every process tag without
    coordination."""
    req = Header(command=int(Command.request), client=0xC11E27,
                 checksum=0xABCDEF)
    prepare = Header(command=int(Command.prepare), client=0xC11E27,
                     context=0xABCDEF)
    reply = Header(command=int(Command.reply), client=0xC11E27,
                   context=0xABCDEF)
    assert req.trace() == prepare.trace() == reply.trace()
    assert req.trace() == trace_id(0xC11E27, 0xABCDEF)
    assert trace_id(1, 2) != trace_id(2, 1)
    assert trace_id(0, 0) != 0  # 0 stays the untraced sentinel


def test_cluster_causal_flows_across_replicas(tmp_path):
    """One transfer through a 3-replica cluster, each replica tracing
    into its own ring: the stitched file links the op's quorum wait,
    journal writes, dispatch and finalize ACROSS replica pids as one
    flow."""
    from tigerbeetle_tpu.models.oracle import OracleStateMachine
    from tigerbeetle_tpu.testing.cluster import Cluster

    tracers = [JsonTracer(pid=i) for i in range(3)]
    cluster = Cluster(replica_count=3, backend_factory=OracleStateMachine,
                      tracer_factory=lambda i: tracers[i])
    client = cluster.add_client()
    cluster.execute(client, Operation.create_accounts,
                    _accounts([1, 2]).tobytes())
    hdr, _ = cluster.execute(client, Operation.create_transfers,
                             _transfer(100).tobytes())
    cluster.run_ticks(5)
    tid = trace_id(client.client_id, hdr.context)
    assert hdr.trace() == tid  # the reply carries the anchor back

    path = str(tmp_path / "cluster.json")
    dump_stitched(path, [tr.events_ordered() for tr in tracers],
                  labels=[f"replica {i}" for i in range(3)])
    events = json.load(open(path))["traceEvents"]
    tagged = [
        (e["pid"], e["name"]) for e in events
        if (e.get("args") or {}).get("trace") == tid
    ]
    # the op's legs span every replica...
    assert {p for p, _ in tagged} == {0, 1, 2}, tagged
    # ...and cover the whole commit path on the primary
    names = {n for _, n in tagged}
    assert {"replica.quorum_wait", "journal.write_prepare",
            "replica.commit_dispatch", "replica.commit_finalize"} <= names
    # connected flow events with this id, well-formed s..f
    assert f"{tid:x}" in _flow_ids(events)
    _assert_flows_well_formed(events)
    # process_name metadata names the pids
    meta = {e["pid"]: e["args"]["name"] for e in events
            if e.get("ph") == "M" and e["name"] == "process_name"}
    assert meta == {0: "replica 0", 1: "replica 1", 2: "replica 2"}


def test_dual_mode_transfer_full_causal_chain(tmp_path):
    """The acceptance chain: one transfer through a 3-replica cluster in
    DUAL mode (native serves, device follows) with a live CDC consumer —
    the stitched trace links quorum -> journal write -> commit dispatch
    -> finalize (reply) -> CDC emit -> device apply (shadow.upload, the
    dispatch the hash-log ring fold rides) under one trace id, across
    pids."""
    from tigerbeetle_tpu.cdc import CdcPump, MemoryCursor
    from tigerbeetle_tpu.cdc.sink import MemorySink
    from tigerbeetle_tpu.models.dual_ledger import DualLedger
    from tigerbeetle_tpu.testing.cluster import Cluster

    tracers = [JsonTracer(pid=i) for i in range(3)]
    cluster = Cluster(
        replica_count=3,
        backend_factory=lambda: DualLedger(12, 14, follower=True),
        tracer_factory=lambda i: tracers[i],
    )
    r0 = cluster.replicas[0]
    assert r0._dual_apply
    r0.cdc_retain = True
    sink = MemorySink()
    pump = CdcPump(r0, sink, MemoryCursor(), window=32)
    pump.attach()

    client = cluster.add_client()
    cluster.execute(client, Operation.create_accounts,
                    _accounts([1, 2]).tobytes())
    hdr, body = cluster.execute(client, Operation.create_transfers,
                                _transfer(100).tobytes())
    assert body == b""  # committed clean
    cluster.run_ticks(5)
    pump.pump(budget_ops=16)
    for r in cluster.replicas:
        assert r.ledger.drain_applier(120)

    tid = trace_id(client.client_id, hdr.context)
    path = str(tmp_path / "dual.json")
    dump_stitched(path, [tr.events_ordered() for tr in tracers],
                  labels=[f"replica {i}" for i in range(3)])
    events = json.load(open(path))["traceEvents"]
    tagged = [
        (e["pid"], e["name"]) for e in events
        if (e.get("args") or {}).get("trace") == tid
    ]
    names0 = {n for p, n in tagged if p == 0}
    assert {"replica.quorum_wait", "journal.write_prepare",
            "replica.commit_dispatch", "replica.commit_finalize",
            "cdc.emit", "shadow.upload"} <= names0, sorted(names0)
    assert {p for p, _ in tagged} == {0, 1, 2}
    assert f"{tid:x}" in _flow_ids(events)
    _assert_flows_well_formed(events)


def test_ring_overflow_leaves_no_dangling_flows(tmp_path):
    """A ring smaller than the span load overwrites oldest-first; the
    stitched output still parses and every surviving flow id has a
    complete s..f leg sequence (flows are generated FROM surviving
    spans, so a dangling reference is impossible by construction)."""
    tr = JsonTracer(capacity=16)
    for i in range(200):
        t = trace_id(i % 40, i // 40)
        with tr.span("stage_a", op=i, trace=t):
            pass
        with tr.span("stage_b", op=i, trace=t):
            pass
    path = str(tmp_path / "ring.json")
    dump_stitched(path, [tr.events_ordered()], labels=["ring"])
    events = json.load(open(path))["traceEvents"]
    spans = [e for e in events if e["ph"] in ("X", "B")]
    assert len(spans) == 16  # the ring kept only the newest tail
    _assert_flows_well_formed(events)
    # no flow references a span that was overwritten out of the ring
    surviving = set()
    for e in spans:
        t = (e.get("args") or {}).get("trace")
        if t:
            surviving.add(f"{t:x}")
    assert _flow_ids(events) <= surviving


def test_stitch_is_deterministic(tmp_path):
    """Stitching the same inputs twice is byte-identical — the property
    the simulator's same-seed reproducibility rests on."""
    tr = JsonTracer(capacity=32, clock=iter(range(10_000)).__next__,
                    ts_div=1.0)
    for i in range(10):
        with tr.span("s", trace=trace_id(i % 3, 7)):
            pass
    ev = tr.events_ordered()
    p1, p2 = str(tmp_path / "a.json"), str(tmp_path / "b.json")
    dump_stitched(p1, [ev, ev], labels=["x", "y"])
    dump_stitched(p2, [ev, ev], labels=["x", "y"])
    assert open(p1, "rb").read() == open(p2, "rb").read()


def test_bus_tags_ingress_parse_and_reply_flush(tmp_path):
    """The TCP bus's frame_parse span carries the trace ids of the
    request frames it dispatched (ingress), and the flush span carries
    the ids of the reply frames it sent (egress) — the wire hops of an
    op's causal tree."""
    from tigerbeetle_tpu.benchmark import free_port
    from tigerbeetle_tpu.io.message_bus import TCPMessageBus

    port = free_port()
    bus = TCPMessageBus([("127.0.0.1", port)], 0, listen=True)
    tracer = JsonTracer()
    bus.tracer = tracer
    bus.attach(0, lambda src, frame: None)
    cid = 0x5E551017
    req = Header(command=int(Command.request), client=cid, request=3,
                 operation=int(Operation.create_accounts))
    req.set_checksum_body(b"")
    req.set_checksum()
    s = socket.create_connection(("127.0.0.1", port))
    try:
        s.sendall(req.to_bytes())
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            if bus.pump(timeout=0.05):
                break
        parses = [e for e in tracer.events_ordered()
                  if e["name"] == "bus.frame_parse"]
        want = trace_id(cid, req.checksum)
        assert any(
            want in (e.get("args") or {}).get("traces", ())
            for e in parses
        ), parses

        # now a reply back to that session: the flush span carries it
        reply = Header(command=int(Command.reply), client=cid,
                       context=req.checksum, request=3)
        reply.set_checksum_body(b"")
        reply.set_checksum()
        assert bus.send(0, cid, reply.to_bytes()) == "sent"
        bus.flush_pending()
        flushes = [e for e in tracer.events_ordered()
                   if e["name"] == "bus.flush"]
        assert any(
            want in (e.get("args") or {}).get("traces", ())
            for e in flushes
        ), flushes
    finally:
        s.close()
        bus.sel.close()


def test_bus_eager_flush_keeps_trace_ids_per_connection():
    """Reply trace ids are tracked PER CONNECTION: a large reply that
    triggers the eager in-send flush of ITS conn must not steal (or be
    mislabeled with) another connection's queued reply ids."""
    from tigerbeetle_tpu.benchmark import free_port
    from tigerbeetle_tpu.io.message_bus import TCPMessageBus

    port = free_port()
    bus = TCPMessageBus([("127.0.0.1", port)], 0, listen=True)
    tracer = JsonTracer()
    bus.tracer = tracer
    bus.attach(0, lambda src, frame: None)

    def connect(cid):
        req = Header(command=int(Command.request), client=cid, request=1,
                     operation=int(Operation.create_accounts))
        req.set_checksum_body(b"")
        req.set_checksum()
        s = socket.create_connection(("127.0.0.1", port))
        s.sendall(req.to_bytes())
        deadline = time.monotonic() + 5
        while cid not in bus.conns and time.monotonic() < deadline:
            bus.pump(timeout=0.05)
        assert cid in bus.conns
        return s

    cid_a, cid_b = 0xAAA0, 0xBBB0
    sa, sb = connect(cid_a), connect(cid_b)
    try:
        def reply_to(cid, body):
            r = Header(command=int(Command.reply), client=cid,
                       context=cid * 7 + 1, request=1)
            r.set_checksum_body(body)
            r.set_checksum()
            return r

        ra = reply_to(cid_a, b"")
        assert bus.send(0, cid_a, ra.to_bytes()) == "sent"  # small: queued
        big = reply_to(cid_b, b"\0" * bus.FLUSH_EAGER)  # eager: flushes B
        assert bus.send(0, cid_b, big.to_bytes() + b"\0" * bus.FLUSH_EAGER) \
            == "sent"
        tid_a = trace_id(cid_a, ra.context)
        tid_b = trace_id(cid_b, big.context)
        flushes = [
            (e.get("args") or {}).get("traces", [])
            for e in tracer.events_ordered() if e["name"] == "bus.flush"
        ]
        eager = [t for t in flushes if tid_b in t]
        assert eager and all(tid_a not in t for t in eager), flushes
        bus.flush_pending()  # A's queued reply flushes with A's id
        flushes = [
            (e.get("args") or {}).get("traces", [])
            for e in tracer.events_ordered() if e["name"] == "bus.flush"
        ]
        assert any(tid_a in t for t in flushes), flushes
    finally:
        sa.close()
        sb.close()
        bus.sel.close()


def test_stitch_trace_cli(tmp_path):
    """scripts/stitch_trace.py merges per-process dumps into one
    Perfetto file with cross-pid flows, deterministically."""
    tr0 = JsonTracer(clock=iter(range(10_000)).__next__, ts_div=1.0)
    tr1 = JsonTracer(clock=iter(range(10_000)).__next__, ts_div=1.0)
    t = trace_id(9, 9)
    with tr0.span("ingress", trace=t):
        pass
    with tr1.span("apply", trace=t):
        pass
    p0, p1 = str(tmp_path / "r0.json"), str(tmp_path / "r1.json")
    tr0.dump(p0)
    tr1.dump(p1)
    out1, out2 = str(tmp_path / "o1.json"), str(tmp_path / "o2.json")
    for out in (out1, out2):
        res = subprocess.run(
            [sys.executable, "scripts/stitch_trace.py",
             "--out", out, p0, p1],
            capture_output=True, text=True, timeout=120, cwd=REPO,
        )
        assert res.returncode == 0, res.stderr
    assert open(out1, "rb").read() == open(out2, "rb").read()
    events = json.load(open(out1))["traceEvents"]
    pids = {e["pid"] for e in events if e["ph"] in ("X", "B")}
    assert pids == {0, 1}
    assert f"{t:x}" in _flow_ids(events)
    _assert_flows_well_formed(events)


@pytest.mark.slow
def test_sim_stitched_trace_multi_pid():
    """The simulator's per-replica tracers stitch into one multi-pid
    file (the fast byte-identity proof lives in test_metrics)."""
    import tempfile

    from tigerbeetle_tpu.testing.simulator import Simulator

    with tempfile.TemporaryDirectory() as d:
        path = f"{d}/sim.json"
        Simulator(31337, ticks=300, trace_path=path).run()
        events = json.load(open(path))["traceEvents"]
        pids = {e["pid"] for e in events if e["ph"] in ("X", "B")}
        assert len(pids) >= 2
        _assert_flows_well_formed(events)
