"""Change-data-capture subsystem (tigerbeetle_tpu/cdc): the encoder's
exact deltas and canonical lines, cursor durability, AOF torn-tail
tolerance, the commit-hook exactly-once contract across repair/catchup/
state-sync, live tail + resume + backpressure through a real cluster, the
CLI replay tool, and the simulator consumer's no-gap/no-dup guarantees."""

import io
import json
import sys

import numpy as np
import pytest

from tigerbeetle_tpu import types
from tigerbeetle_tpu.cdc import (
    CdcPump,
    FileCursor,
    MemoryCursor,
    MemorySink,
    encode_batch,
    record_line,
)
from tigerbeetle_tpu.models.oracle import OracleStateMachine
from tigerbeetle_tpu.testing.cluster import Cluster
from tigerbeetle_tpu.types import (
    CreateTransferResult,
    Operation,
    TransferFlags,
)
from tigerbeetle_tpu.vsr.header import Command, Header


def _prepare_header(op, operation, timestamp) -> Header:
    return Header(
        command=int(Command.prepare), op=op,
        operation=int(operation), timestamp=timestamp,
    )


# ---------------------------------------------------------------- encoder


def test_record_encoder_exact_deltas_and_canonical_lines():
    transfers = [
        types.Transfer(id=10, debit_account_id=1, credit_account_id=2,
                       amount=7, ledger=1, code=1),
        types.Transfer(id=11, debit_account_id=2, credit_account_id=3,
                       amount=5, ledger=1, code=1,
                       flags=int(TransferFlags.pending)),
    ]
    body = types.transfers_to_np(transfers).tobytes()
    h = _prepare_header(9, Operation.create_transfers, 1000)
    recs = encode_batch(h, body, b"")  # empty reply: all ok
    assert [r["ts"] for r in recs] == [999, 1000]  # ts - n + i + 1
    assert recs[0]["deltas"] == [
        [1, "debits_posted", 7], [2, "credits_posted", 7],
    ]
    assert recs[1]["deltas"] == [
        [2, "debits_pending", 5], [3, "credits_pending", 5],
    ]
    assert all(r["resolved"] and r["result"] == 0 for r in recs)
    # canonical: stable bytes, loadable, op/ix present
    lines = [record_line(r) for r in recs]
    assert lines == [record_line(r) for r in recs]
    assert json.loads(lines[0])["op"] == 9


def test_record_encoder_failed_and_indirect_events():
    transfers = [
        types.Transfer(id=20, debit_account_id=1, credit_account_id=2,
                       amount=3, ledger=1, code=1),
        types.Transfer(id=21, pending_id=11,
                       flags=int(TransferFlags.post_pending_transfer)),
    ]
    body = types.transfers_to_np(transfers).tobytes()
    reply = np.zeros(1, dtype=types.CREATE_TRANSFERS_RESULT_DTYPE)
    reply[0]["index"] = 0
    reply[0]["result"] = int(CreateTransferResult.exists)
    recs = encode_batch(
        _prepare_header(3, Operation.create_transfers, 50),
        body, reply.tobytes(),
    )
    # failed: exactly zero effect, known exactly
    assert recs[0]["result"] == int(CreateTransferResult.exists)
    assert recs[0]["resolved"] and "deltas" not in recs[0]
    # post_pending: amount resolves against the pending transfer's state
    assert recs[1]["result"] == 0
    assert not recs[1]["resolved"] and "deltas" not in recs[1]
    # unknown reply buffer: result null, unresolved
    recs = encode_batch(
        _prepare_header(3, Operation.create_transfers, 50), body, None
    )
    assert all(r["result"] is None and not r["resolved"] for r in recs)
    # non-change ops encode to nothing
    assert encode_batch(
        _prepare_header(1, Operation.register, 1), b"", b""
    ) == []


# ----------------------------------------------------------------- cursor


def test_file_cursor_roundtrip_and_corrupt_fallback(tmp_path):
    path = str(tmp_path / "consumer.cursor")
    c = FileCursor(path)
    assert c.load() == (0, 0)  # absent
    c.ack(42, 0xDEADBEEF << 64)
    assert FileCursor(path).load() == (42, 0xDEADBEEF << 64)
    c.ack(43, 7)  # atomic replace, no tmp residue
    assert not (tmp_path / "consumer.cursor.tmp").exists()
    assert c.load() == (43, 7)
    # corruption reads as absent (with a warning), never raises
    with open(path, "r+b") as f:
        f.seek(10)
        f.write(b"XX")
    err = io.StringIO()
    orig, sys.stderr = sys.stderr, err
    try:
        assert FileCursor(path).load() == (0, 0)
    finally:
        sys.stderr = orig
    assert "corrupt" in err.getvalue()


# ------------------------------------------------------- AOF torn tails


def test_aof_replay_tolerates_truncation_at_every_tail_offset(tmp_path):
    from tigerbeetle_tpu.aof import AOF, SECTOR, replay

    path = str(tmp_path / "log.aof")
    aof = AOF(path)
    headers = []
    for op in (1, 2, 3):
        t = types.Transfer(id=op, debit_account_id=1, credit_account_id=2,
                           amount=1, ledger=1, code=1)
        body = types.transfers_to_np([t]).tobytes()
        h = _prepare_header(op, Operation.create_transfers, 100 + op)
        h.set_checksum_body(body)
        h.set_checksum()
        aof.append(h, body)
        headers.append(h)
    aof.close()
    data = open(path, "rb").read()
    assert len(data) == 3 * SECTOR
    whole = list(replay(path))
    assert [h.op for h, _ in whole] == [1, 2, 3]

    record_len = 16 + 128 + 128  # magic+size, header, 1-transfer body
    err = io.StringIO()
    orig, sys.stderr = sys.stderr, err
    try:
        # crash mid-append of record 3: every byte offset of the final
        # record must stop the replay cleanly — never raise. A cut inside
        # the record loses it (replay ends at record 2); a cut inside the
        # trailing zero PAD leaves the record complete and replayable.
        for cut in range(2 * SECTOR, 3 * SECTOR):
            with open(path, "r+b") as f:
                f.truncate(cut)
                f.seek(0, 2)
            got = list(replay(path))
            want = [1, 2] if cut < 2 * SECTOR + record_len else [1, 2, 3]
            assert [h.op for h, _ in got] == want, cut
            # restore for the next cut
            with open(path, "r+b") as f:
                f.write(data)
    finally:
        sys.stderr = orig
    # a cut strictly inside the record leaves trailing bytes: warned
    assert "torn/corrupt tail" in err.getvalue()


# ----------------------------------- hook exactly-once across all paths


def _oracle_cluster(replica_count=3, **kw):
    return Cluster(replica_count=replica_count,
                   backend_factory=OracleStateMachine, **kw)


def _drive_batches(cluster, client, start_id, n_batches, batch=2):
    for k in range(n_batches):
        ts = [
            types.Transfer(
                id=start_id + k * batch + j, debit_account_id=1,
                credit_account_id=2, amount=1, ledger=1, code=1,
            )
            for j in range(batch)
        ]
        _h, body = cluster.execute(
            client, Operation.create_transfers,
            types.transfers_to_np(ts).tobytes(),
        )
        assert body == b""


def test_commit_hooks_fire_exactly_once_across_repair_catchup_and_sync(
    tmp_path,
):
    """The contract documented at replica._commit_dispatch_inner: the
    commit hook, the AOF append, and the CDC finalize hook each fire
    EXACTLY once per op within a process lifetime — through the normal
    path, through catchup after a partition (journal-gap repair fills via
    request_prepare), and through a state-sync install, which commits
    NONE of the jumped ops (they fire zero times, by design: the CDC pump
    declares them as a gap)."""
    from tigerbeetle_tpu.aof import AOF, replay

    cl = _oracle_cluster()
    counts = [{} for _ in cl.replicas]  # replica -> op -> commit_hook fires
    cdc_counts = [{} for _ in cl.replicas]
    for i, r in enumerate(cl.replicas):
        def commit_hook(h, b, _c=counts[i]):
            _c[h.op] = _c.get(h.op, 0) + 1

        def cdc_hook(h, b, reply, _c=cdc_counts[i]):
            _c[h.op] = _c.get(h.op, 0) + 1

        r.commit_hook = commit_hook
        r.cdc_hook = cdc_hook
    aof_path = str(tmp_path / "r0.aof")
    cl.replicas[0].aof = AOF(aof_path)

    c = cl.add_client()
    accounts = [types.Account(id=i, ledger=1, code=1) for i in (1, 2)]
    cl.execute(c, Operation.create_accounts,
               types.accounts_to_np(accounts).tobytes())

    # normal path
    _drive_batches(cl, c, 1000, 3)
    # catchup: replica 2 misses a few ops, then repairs + commits them
    cl.detach_replica(2)
    _drive_batches(cl, c, 2000, 4)
    cl.reattach_replica(2)
    cl.run_ticks(30)
    base_commit = cl.replicas[2].commit_min
    assert base_commit == cl.replicas[0].commit_min
    # state sync: replica 2 misses > checkpoint_interval (60) ops — on
    # reattach it installs the checkpoint image and commits the tail only
    cl.detach_replica(2)
    interval = cl.cluster_config.checkpoint_interval
    _drive_batches(cl, c, 10_000, interval + 10)
    cl.reattach_replica(2)
    for _ in range(20):
        cl.run_ticks(10)
        if cl.replicas[2].commit_min == cl.replicas[0].commit_min:
            break
    assert cl.replicas[2].commit_min == cl.replicas[0].commit_min

    top = cl.replicas[0].commit_min
    for i in (0, 1):
        ops = set(counts[i])
        assert ops == set(range(1, top + 1))
        assert set(counts[i].values()) == {1}, f"replica {i} duplicated"
        assert counts[i] == cdc_counts[i]
    # replica 2: every fired op fired ONCE; the state-sync jump fired none
    assert set(counts[2].values()) == {1}, "replica 2 duplicated a commit"
    assert counts[2] == cdc_counts[2]
    jumped = set(range(base_commit + 1, cl.replicas[2].checkpoint_op + 1))
    assert jumped and not (jumped & set(counts[2])), (
        "state-sync install must not re-fire hooks for jumped ops"
    )
    # the AOF holds replica 0's ops exactly once each
    aof_ops = [h.op for h, _ in replay(aof_path)]
    assert aof_ops == sorted(set(aof_ops))
    assert set(aof_ops) == set(range(1, top + 1))


# -------------------------------------------- pump: live tail + resume


def _expected_lines(replica, lo, hi):
    out = []
    for op in range(lo, hi + 1):
        h, body = replica.journal.read_prepare(op)
        reply = replica.cdc_replies.get(op)
        out += [record_line(r) for r in encode_batch(h, body, reply)]
    return out


def test_pump_live_tail_window_eviction_and_resume():
    cl = _oracle_cluster(replica_count=1)
    r = cl.replicas[0]
    sink, cursor = MemorySink(), MemoryCursor()
    pump = CdcPump(r, sink, cursor, window=2, ack_interval=2)
    pump.attach()
    c = cl.add_client()
    accounts = [types.Account(id=i, ledger=1, code=1) for i in (1, 2)]
    cl.execute(c, Operation.create_accounts,
               types.accounts_to_np(accounts).tobytes())
    _drive_batches(cl, c, 100, 4)
    # a duplicate id: a non-empty reply body must survive the live-window
    # eviction through the replica's cdc_replies ring
    dup = types.Transfer(id=100, debit_account_id=1, credit_account_id=2,
                         amount=1, ledger=1, code=1)
    _h, reply = cl.execute(c, Operation.create_transfers,
                           types.transfers_to_np([dup]).tobytes())
    assert reply != b""
    # window=2 but 6 ops committed: the pump serves evictions from the WAL
    while pump.pump(budget_ops=4):
        pass
    m = r.metrics.snapshot()["counters"]
    assert m["cdc.journal_reads"] > 0 and m["cdc.live_hits"] > 0
    assert sink.lines == _expected_lines(r, 1, r.commit_min)
    dup_rec = json.loads(sink.lines[-1])
    assert dup_rec["result"] == int(CreateTransferResult.exists)

    # consumer restart: progress past the cursor ack is REDELIVERED and
    # dedupable by op; the stream continues with no gap
    acked_op, _ = cursor.load()
    assert acked_op >= 2
    pump.detach()
    seen_before = {json.loads(line)["op"] for line in sink.lines}
    sink2 = MemorySink()
    pump2 = CdcPump(r, sink2, cursor, window=4, ack_interval=2)
    pump2.attach()
    _drive_batches(cl, c, 200, 2)
    while pump2.pump(budget_ops=4):
        pass
    ops2 = [json.loads(line)["op"] for line in sink2.lines]
    assert ops2 == sorted(ops2)
    assert min(ops2) == acked_op + 1  # redelivery starts after the ack
    assert set(o for o in ops2 if o <= r.commit_min) | seen_before == {
        op for op in range(2, r.commit_min + 1)
    }  # op 1 is the register: record-less
    # full redelivered content matches the original stream where they
    # overlap (dedup by op is sufficient — content is identical)
    overlap = [line for line in sink2.lines
               if json.loads(line)["op"] in seen_before]
    assert overlap == [line for line in sink.lines
                       if json.loads(line)["op"] > acked_op]


def test_pump_backpressure_pauses_pump_never_replica():
    cl = _oracle_cluster(replica_count=1)
    r = cl.replicas[0]
    sink = MemorySink(capacity=3)  # refuses once 3 lines are buffered
    pump = CdcPump(r, sink, MemoryCursor(), window=64)
    pump.attach()
    c = cl.add_client()
    accounts = [types.Account(id=i, ledger=1, code=1) for i in (1, 2)]
    cl.execute(c, Operation.create_accounts,
               types.accounts_to_np(accounts).tobytes())
    commits_before = r.commit_min
    _drive_batches(cl, c, 300, 5)
    assert r.commit_min == commits_before + 5  # replica never paused
    for _ in range(4):
        pump.pump()  # repeated refusals: ONE pause transition
    m = r.metrics.snapshot()
    assert m["counters"]["cdc.backpressure_pauses"] == 1
    assert m["gauges"]["cdc.lag_ops"] > 0
    stalled = len(sink.lines)
    sink.capacity = None  # consumer catches up
    while pump.pump(budget_ops=8):
        pass
    assert len(sink.lines) > stalled
    assert sink.lines == _expected_lines(r, 1, r.commit_min)
    assert r.metrics.snapshot()["gauges"]["cdc.lag_ops"] == 0


def test_udp_sink_reuses_statsd_mtu_batching():
    import socket

    from tigerbeetle_tpu.cdc import UdpSink

    rx = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    rx.bind(("127.0.0.1", 0))
    rx.settimeout(2)
    sink = UdpSink("127.0.0.1", rx.getsockname()[1])
    lines = [record_line({"op": i, "kind": "transfer", "x": "y" * 80})
             for i in range(40)]
    assert sink.emit_lines(lines)
    assert sink.datagrams >= 2  # MTU-packed, not one datagram per line
    got = []
    for _ in range(sink.datagrams):
        payload = rx.recv(2048)
        assert len(payload) <= 1400
        got += payload.decode().split("\n")
    assert got == lines  # order and framing survive the packing
    sink.close()
    rx.close()


def test_aof_replay_source_serves_across_a_hole(tmp_path):
    """An AOF hole (ops the replica never executed — a state-sync jump)
    must not swallow the first record beyond it: read() keeps a lookahead,
    next_available() bounds the declared gap, and the CLI backfill emits
    an explicit gap record then continues (the reviewed failure mode:
    AOF-covered history mis-declared as gone)."""
    from tigerbeetle_tpu.aof import AOF
    from tigerbeetle_tpu.cdc import AofReplaySource
    from tigerbeetle_tpu.cli import main as cli_main

    path = str(tmp_path / "holed.aof")
    aof = AOF(path)
    for op in (1, 2, 5, 6):  # ops 3-4 never executed here
        t = types.Transfer(id=op, debit_account_id=1, credit_account_id=2,
                           amount=1, ledger=1, code=1)
        body = types.transfers_to_np([t]).tobytes()
        h = _prepare_header(op, Operation.create_transfers, 100 + op)
        h.set_checksum_body(body)
        h.set_checksum()
        aof.append(h, body)
    aof.close()

    src = AofReplaySource(path)
    assert src.read(1)[0].op == 1
    assert src.read(2)[0].op == 2
    assert src.read(3) is None  # the hole...
    assert src.next_available() == 5  # ...bounded where the log resumes
    assert src.read(4) is None
    got = src.read(5)
    assert got is not None and got[0].op == 5  # lookahead not dropped
    assert src.read(6)[0].op == 6

    out = str(tmp_path / "holed.jsonl")
    assert cli_main(["cdc", "--sink", f"jsonl:{out}", path]) == 0
    recs = [json.loads(line) for line in open(out).read().splitlines()]
    kinds = [(r.get("kind"), r.get("op", r.get("from"))) for r in recs]
    assert kinds == [
        ("transfer", 1), ("transfer", 2), ("gap", 3),
        ("transfer", 5), ("transfer", 6),
    ]
    assert recs[2] == {"kind": "gap", "from": 3, "to": 4}


# ------------------------------------------------------------ CLI replay


def test_cdc_cli_replays_aof_with_cursor_resume(tmp_path, capsys):
    from tigerbeetle_tpu.aof import AOF
    from tigerbeetle_tpu.cli import main as cli_main

    cl = _oracle_cluster(replica_count=1)
    r = cl.replicas[0]
    aof_path = str(tmp_path / "log.aof")
    r.aof = AOF(aof_path)
    live_sink = MemorySink()
    pump = CdcPump(r, live_sink, MemoryCursor())
    pump.attach()
    c = cl.add_client()
    accounts = [types.Account(id=i, ledger=1, code=1) for i in (1, 2)]
    cl.execute(c, Operation.create_accounts,
               types.accounts_to_np(accounts).tobytes())
    _drive_batches(cl, c, 500, 3)
    # one failed event so oracle-derived result codes are actually tested
    dup = types.Transfer(id=500, debit_account_id=1, credit_account_id=2,
                         amount=1, ledger=1, code=1)
    cl.execute(c, Operation.create_transfers,
               types.transfers_to_np([dup]).tobytes())
    while pump.pump(budget_ops=8):
        pass
    r.aof.close()

    out_path = str(tmp_path / "stream.jsonl")
    rc = cli_main(["cdc", "--sink", f"jsonl:{out_path}", aof_path])
    assert rc == 0
    replayed = open(out_path).read().splitlines()
    # the offline oracle replay reproduces the live stream byte for byte
    assert replayed == live_sink.lines
    # resume: the cursor is at the end — a second run emits nothing new
    rc = cli_main(["cdc", "--sink", f"jsonl:{out_path}", aof_path])
    assert rc == 0
    assert open(out_path).read().splitlines() == replayed
    assert "0 records over 0 ops" in capsys.readouterr().err


# ------------------------------------------------- simulator consumer


def test_simulator_cdc_consumer_crash_restart_no_gaps_no_dup_effects():
    """The acceptance run: the VOPR crashes/restarts the CDC consumer
    mid-stream (and replicas too); the checker inside Simulator._check
    proves coverage with zero gaps and apply-once effects, and two
    same-seed runs dump byte-identical streams."""
    from tigerbeetle_tpu.testing.simulator import Simulator

    dumps = []
    stats = None
    for _ in range(2):
        sim = Simulator(7, ticks=500, cdc_consumer=True,
                        cdc_crash_probability=0.02)
        stats = sim.run()  # _check_cdc runs inside
        dumps.append("\n".join(sim.cdc.stream))
    assert stats["cdc_crashes"] >= 1, "consumer never crashed mid-stream"
    assert stats["cdc_redelivered_ops"] >= 1, (
        "no crash landed between sink-accept and cursor-ack; the dedup "
        "contract went unexercised"
    )
    assert stats["cdc_gaps"] == 0
    assert stats["cdc_records"] > 0
    assert dumps[0] == dumps[1], "same seed must dump identical streams"


@pytest.mark.slow
def test_simulator_cdc_more_seeds():
    from tigerbeetle_tpu.testing.simulator import run_simulation

    for seed in (3, 11, 42):
        stats = run_simulation(seed, ticks=700, cdc_consumer=True)
        assert stats["cdc_records"] > 0
