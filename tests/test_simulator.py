"""The VOPR-equivalent simulator (reference: src/simulator.zig; SURVEY §4
tier 3): seeded end-to-end cluster runs under crashes, partitions, packet
loss/replay/reorder, and WAL fault injection, checked for one linear
history, convergence, and bit-exact oracle parity."""

import pytest

from tigerbeetle_tpu.testing.simulator import run_simulation


@pytest.mark.parametrize("seed", [1, 2, 3, 7, 14])
def test_simulation_seeds(seed):
    # progress floor: seed 7 sits at 19 ops with the client runtime's
    # jittered backoff (was 20+ with the old flat resend cadence)
    stats = run_simulation(seed, ticks=600)
    assert stats["committed_ops"] > 15
    assert stats["replies"] > 10


def test_simulation_deterministic():
    """Same seed => identical run (the property that makes failures
    replayable; reference: src/simulator.zig:66-71)."""
    a = run_simulation(42, ticks=400)
    b = run_simulation(42, ticks=400)
    assert a == b


def test_simulation_heavy_faults():
    """Aggressive loss + partitions still converge."""
    from tigerbeetle_tpu.testing.packet_simulator import PacketSimulatorOptions

    stats = run_simulation(
        5,
        ticks=700,
        crash_probability=0.004,
        options=PacketSimulatorOptions(
            packet_loss_probability=0.05,
            packet_replay_probability=0.05,
            partition_probability=0.01,
        ),
    )
    assert stats["committed_ops"] > 10


def test_simulation_wal_faults_exercised():
    """Crash-heavy run with guaranteed WAL corruption on restart: the
    journal's faulty-slot detection + peer repair must carry the run."""
    stats = run_simulation(
        9,
        ticks=900,
        crash_probability=0.008,
        restart_ticks_max=40,
        wal_fault_probability=1.0,
    )
    assert stats["crashes"] >= 2
    assert stats["wal_faults"] >= 1
    assert stats["committed_ops"] > 20


def test_simulation_device_backend():
    """One seed with the REAL device-ledger backend behind every replica
    (slow: jit commits on the CPU mesh) — the TPU kernels under consensus,
    crashes and all."""
    stats = run_simulation(
        3,
        ticks=260,
        backend_factory=None,  # default: DeviceLedger
        n_clients=1,
        crash_probability=0.003,
    )
    assert stats["committed_ops"] > 5


def test_simulation_torn_writes_and_zone_faults():
    """Crash-point torn writes (prepare and/or redundant header cut at
    crash) plus client_replies + superblock copy corruption on restart —
    the full zone fault envelope under the atlas rule (reference:
    src/testing/storage.zig:1-25, src/simulator.zig:160-173)."""
    stats = run_simulation(
        11,
        ticks=900,
        crash_probability=0.008,
        restart_ticks_max=40,
        torn_write_probability=1.0,
        replies_fault_probability=0.5,
        superblock_fault_probability=0.5,
    )
    assert stats["crashes"] >= 2
    assert (
        stats["torn_writes"] + stats["replies_faults"]
        + stats["superblock_faults"] >= 2
    )
    assert stats["committed_ops"] > 20


def test_simulation_five_replicas():
    """A 5-replica cluster (quorum 3) under crashes and the widened
    partition modes (isolate-single / uniform-size / single-link,
    symmetric and asymmetric)."""
    from tigerbeetle_tpu.testing.packet_simulator import PacketSimulatorOptions

    stats = run_simulation(
        17,
        ticks=800,
        replica_count=5,
        crash_probability=0.004,
        options=PacketSimulatorOptions(
            packet_loss_probability=0.02,
            packet_replay_probability=0.02,
            partition_probability=0.01,
        ),
    )
    assert stats["committed_ops"] > 20


def test_simulation_with_standbys():
    """Standbys under chaos (reference: VOPR runs standbys too): they
    follow the log (streamed prepares), never vote, crash/restart freely
    outside quorum accounting, and converge to the same committed state."""
    stats = run_simulation(
        29,
        ticks=900,
        replica_count=3,
        standby_count=2,
        n_clients=2,
        crash_probability=0.004,
    )
    assert stats["committed_ops"] > 10


def test_simulation_grid_read_latency_off_hot_loop():
    """Injected grid-read latency through the Storage seam must not
    perturb a seeded run: replica behavior keys off virtual time and the
    spill/grid IO rides the deterministic executor, so the committed
    history, reply count, and even the grid-read count are BYTE-IDENTICAL
    with and without per-read latency — the commit cadence is unchanged
    because no hot-loop decision ever waits on a grid read. Also the
    same-seed determinism proof for spill_async IO being lifted in the
    replica (two identical runs agree exactly)."""
    from tigerbeetle_tpu.constants import ConfigProcess

    kwargs = dict(
        ticks=240,
        backend_factory=None,  # DeviceLedger + forest: the spill store
        replica_count=2,
        n_clients=1,
        client_batch=24,
        crash_probability=0.0,
        wal_fault_probability=0.0,
        torn_write_probability=0.0,
        replies_fault_probability=0.0,
        superblock_fault_probability=0.0,
        forest_blocks=192,
        grid_size=64 * 1024 * 1024,
        process=ConfigProcess(
            account_slots_log2=10, transfer_slots_log2=7,
            lsm_memtable_max=48,
        ),
        workload_knobs=dict(
            ledgers=(1,), invalid_rate=0.0, conflict_rate=0.02,
            chain_rate=0.0, two_phase_rate=0.1, balancing_rate=0.0,
            limit_account_rate=0.0,
        ),
    )
    base = run_simulation(7, **kwargs)
    again = run_simulation(7, **kwargs)
    slow = run_simulation(7, grid_read_latency_s=0.0003, **kwargs)
    assert base["committed_ops"] > 5
    assert base["grid_reads"] > 0, "the run never touched the spill store"
    for key in ("committed_ops", "replies", "grid_reads", "view"):
        assert base[key] == again[key], (key, base[key], again[key])
        assert base[key] == slow[key], (key, base[key], slow[key])
