"""Async-WAL crash recovery on a single replica: out-of-order prepare
writes + chain validation at open (ADVICE round-3 high finding).

With commit_window > 0 the single-replica primary writes prepares through
an 8-worker pool (vsr/journal.py), so op N+1's 1 MiB write can land while
op N's is still in flight. A crash in that window leaves a GAP below a
durable higher-op prepare. Recovery must treat the chain as ending at the
gap (no reply can have left for anything above it: replies finalize in op
order, each awaiting its own WAL future), and must DESTROY the stale
higher slots — otherwise a restart that re-fills the gap on a new timeline
leaves a slot that breaks the hash chain and crash-loops the SECOND
restart (reference: src/vsr/journal.zig:374-535 classifies such slots in
its recovery decision matrix).
"""


from tigerbeetle_tpu import types
from tigerbeetle_tpu.testing.cluster import Cluster
from tigerbeetle_tpu.types import Operation
from tigerbeetle_tpu.vsr.header import Command, Header


def _accounts_body(ids):
    return types.accounts_to_np(
        [types.Account(id=i, ledger=1, code=1) for i in ids]
    ).tobytes()


def _craft_prepare(replica, op, parent, timestamp, body):
    h = Header(
        command=int(Command.prepare),
        operation=int(Operation.create_accounts),
        op=op,
        parent=parent,
        timestamp=timestamp,
        view=replica.view,
        replica=replica.replica,
    )
    h.set_checksum_body(body)
    h.set_checksum()
    return h


def test_gap_below_durable_higher_prepare_truncates_and_survives_refill():
    cluster = Cluster(replica_count=1)
    r = cluster.replicas[0]
    client = cluster.add_client()
    cluster.execute(client, Operation.create_accounts, _accounts_body([1, 2]))
    base = r.op
    base_checksum = r.parent_checksum
    ts = r.sm.prepare_timestamp

    # The crash window: op base+1's WAL write was still queued (nothing on
    # disk), op base+2's landed. Craft both prepares on the pre-crash
    # timeline; only the higher one reaches the journal.
    b1 = _accounts_body([100])
    h1 = _craft_prepare(r, base + 1, base_checksum, ts + 10, b1)
    b2 = _accounts_body([101])
    h2 = _craft_prepare(r, base + 2, h1.checksum, ts + 20, b2)
    r.journal.write_prepare(h2, b2)  # out-of-order landing
    r.journal.quiesce()

    # Restart 1: recovery stops at the gap; the stale higher slot must be
    # destroyed (it was never acked — replies finalize in op order).
    r1 = cluster.restart_replica(0)
    assert r1.op == base and r1.commit_min == base
    assert r1.journal.read_prepare(base + 2) is None, (
        "stale-timeline slot above the gap survived recovery"
    )

    # New timeline: re-fill ONLY base+1 (one register op) so a surviving
    # stale base+2 slot would sit right above the new head at restart 2.
    client2 = cluster.add_client()  # register consumes exactly base+1
    committed = r1.commit_min
    assert committed == base + 1

    # Restart 2: previously crash-looped on `assert header.parent` against
    # the stale base+2 slot; now replays the new timeline cleanly.
    r2 = cluster.restart_replica(0)
    assert r2.commit_min == committed
    assert r2.op == committed

    # and the replica still serves
    client3 = cluster.add_client()
    _h, reply = cluster.execute(
        client3, Operation.create_accounts, _accounts_body([300])
    )
    assert reply == b""


def test_mid_log_chain_break_truncates_at_break():
    """A surviving higher slot whose parent does NOT chain from the replay
    head must end the replay (not assert): ops above it are a stale
    timeline."""
    cluster = Cluster(replica_count=1)
    r = cluster.replicas[0]
    client = cluster.add_client()
    cluster.execute(client, Operation.create_accounts, _accounts_body([1]))
    base = r.op
    ts = r.sm.prepare_timestamp

    # A prepare for base+1 whose parent checksum is junk (stale timeline).
    b1 = _accounts_body([110])
    h1 = _craft_prepare(r, base + 1, 0xDEADBEEF, ts + 10, b1)
    r.journal.write_prepare(h1, b1)
    r.journal.quiesce()

    r1 = cluster.restart_replica(0)
    assert r1.op == base and r1.commit_min == base
    assert r1.journal.read_prepare(base + 1) is None
