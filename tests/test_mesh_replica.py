"""The sharded ledger BEHIND the StateMachine seam (VERDICT r3 item 3):
a replica whose commit backend is the multi-chip ShardedLedger over the
virtual 8-device CPU mesh — journal + consensus + sharded device commit +
reply, not a bare kernel demo (SURVEY.md §5.8: sharding is an internal
implementation detail behind the StateMachine interface).
"""

import numpy as np
import pytest

from tigerbeetle_tpu import types
from tigerbeetle_tpu.constants import ConfigProcess
from tigerbeetle_tpu.state_machine import decode_accounts, encode_ids
from tigerbeetle_tpu.testing.cluster import Cluster
from tigerbeetle_tpu.types import Operation


@pytest.fixture(scope="module")
def mesh8():
    import jax
    from jax.sharding import Mesh

    devices = jax.devices()
    if len(devices) < 8:
        pytest.skip("needs the 8-device virtual CPU mesh")
    return Mesh(np.array(devices[:8]), ("shard",))


def _factory(mesh8):
    from tigerbeetle_tpu.parallel.mesh import ShardedLedger

    process = ConfigProcess(account_slots_log2=8, transfer_slots_log2=10)
    return lambda: ShardedLedger(mesh8, process)


def test_replica_commits_through_sharded_backend(mesh8):
    factory = _factory(mesh8)
    cluster = Cluster(replica_count=1, backend_factory=factory)
    client = cluster.add_client()

    accounts = [types.Account(id=i, ledger=1, code=1) for i in range(1, 25)]
    _h, reply = cluster.execute(
        client, Operation.create_accounts,
        types.accounts_to_np(accounts).tobytes(),
    )
    assert reply == b""

    xfers = [
        types.Transfer(id=500 + i, debit_account_id=1 + i % 24,
                       credit_account_id=1 + (i + 11) % 24, amount=2,
                       ledger=1, code=1)
        for i in range(48)
    ]
    _h, reply = cluster.execute(
        client, Operation.create_transfers,
        types.transfers_to_np(xfers).tobytes(),
    )
    assert reply == b""

    # lookups through consensus hit the sharded tables (psum-fused finds)
    _h, body = cluster.execute(
        client, Operation.lookup_accounts, encode_ids(list(range(1, 25)))
    )
    rows = decode_accounts(body)
    assert len(rows) == 24
    assert rows["debits_posted_lo"].sum() == 96  # 48 transfers x amount 2
    assert rows["credits_posted_lo"].sum() == 96

    # duplicate submission answers exists codes from the sharded state
    _h, reply = cluster.execute(
        client, Operation.create_transfers,
        types.transfers_to_np(xfers[:4]).tobytes(),
    )
    from tigerbeetle_tpu.state_machine import decode_results

    got = decode_results(reply, Operation.create_transfers)
    assert got == [(i, int(types.CreateTransferResult.exists))
                   for i in range(4)]


def test_sharded_checkpoint_restart_and_resume(mesh8):
    """Checkpoint (sharded snapshot blob) + crash-restart + continue:
    the restored mesh state serves lookups identically and accepts new
    commits (the WAL replay path runs through the sharded backend too)."""
    factory = _factory(mesh8)
    cluster = Cluster(replica_count=1, backend_factory=factory)
    client = cluster.add_client()
    accounts = [types.Account(id=i, ledger=1, code=1) for i in range(1, 9)]
    cluster.execute(
        client, Operation.create_accounts,
        types.accounts_to_np(accounts).tobytes(),
    )
    xfers = [
        types.Transfer(id=900 + i, debit_account_id=1 + i % 8,
                       credit_account_id=1 + (i + 3) % 8, amount=1,
                       ledger=1, code=1)
        for i in range(16)
    ]
    cluster.execute(
        client, Operation.create_transfers,
        types.transfers_to_np(xfers).tobytes(),
    )
    replica = cluster.replicas[0]
    replica.checkpoint()

    # post-checkpoint ops live only in the WAL: replay goes through the
    # sharded backend at open()
    xfers2 = [
        types.Transfer(id=950 + i, debit_account_id=1 + i % 8,
                       credit_account_id=1 + (i + 5) % 8, amount=1,
                       ledger=1, code=1)
        for i in range(8)
    ]
    cluster.execute(
        client, Operation.create_transfers,
        types.transfers_to_np(xfers2).tobytes(),
    )
    before = replica.sm.commit(
        Operation.lookup_accounts, 0, encode_ids(list(range(1, 9)))
    )

    cluster.restart_replica(0, backend_factory=factory)
    client2 = cluster.add_client()
    _h, after = cluster.execute(
        client2, Operation.lookup_accounts, encode_ids(list(range(1, 9)))
    )
    assert after == before
    rows = decode_accounts(after)
    assert rows["debits_posted_lo"].sum() == 24  # 16 + 8 transfers

    # and the restarted sharded replica still commits
    _h, reply = cluster.execute(
        client2, Operation.create_transfers,
        types.transfers_to_np([
            types.Transfer(id=999, debit_account_id=1, credit_account_id=2,
                           amount=5, ledger=1, code=1)
        ]).tobytes(),
    )
    assert reply == b""
