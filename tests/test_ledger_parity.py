"""Bit-exact parity: device ledger kernels vs. the oracle state machine.

The analog of the reference's state-machine unit tier + auditor
(reference: src/state_machine.zig:1181-1299 TestContext,
src/state_machine/auditor.zig): every batch from the randomized workload runs
through both implementations; dense result codes must match exactly, and the
full extracted store state must match periodically.
"""

import pytest

from tigerbeetle_tpu.constants import TEST_PROCESS
from tigerbeetle_tpu.models.ledger import DeviceLedger
from tigerbeetle_tpu.models.oracle import OracleStateMachine
from tigerbeetle_tpu.testing.workload import WorkloadGenerator
from tigerbeetle_tpu.types import Operation, Transfer


def run_parity(seed, n_batches, batch_size, mode, state_every=4, **wl_kwargs):
    oracle = OracleStateMachine()
    dev = DeviceLedger(process=TEST_PROCESS, mode=mode)
    gen = WorkloadGenerator(seed, **wl_kwargs)
    ts = 1_000_000_000
    for b in range(n_batches):
        if b % 4 == 0:
            op, events = gen.gen_accounts_batch(batch_size)
        else:
            op, events = gen.gen_transfers_batch(batch_size)
        ts += len(events)
        dense_o = oracle.execute_dense(op, ts, events)
        dense_d = dev.execute_dense(op, ts, events)
        if dense_d != dense_o:
            diffs = [
                (i, o, d) for i, (o, d) in enumerate(zip(dense_o, dense_d)) if o != d
            ]
            raise AssertionError(f"batch {b} ({op.name}): (idx, oracle, dev) {diffs[:10]}")
        if b % state_every == state_every - 1:
            accounts, transfers, posted = dev.extract()
            assert accounts == oracle.accounts, f"batch {b}: account state diverged"
            assert transfers == oracle.transfers, f"batch {b}: transfer state diverged"
            assert posted == oracle.posted, f"batch {b}: posted state diverged"
            assert dev.commit_timestamp == oracle.commit_timestamp
    return oracle, dev


@pytest.mark.parametrize("seed", [1, 2])
def test_serial_parity(seed):
    run_parity(seed, n_batches=10, batch_size=40, mode="serial")


@pytest.mark.parametrize("seed", [3, 4])
def test_auto_parity(seed):
    run_parity(seed, n_batches=10, batch_size=40, mode="auto")


def test_auto_parity_clean_workload():
    """A hazard-free workload (no chains/two-phase/balancing/limits) exercises
    the vectorized tier under auto dispatch."""
    run_parity(
        5,
        n_batches=8,
        batch_size=40,
        mode="auto",
        chain_rate=0.0,
        two_phase_rate=0.0,
        balancing_rate=0.0,
        limit_account_rate=0.0,
        conflict_rate=0.0,
    )


def test_fast_tier_forced_clean_workload():
    """mode="fast" bypasses the hazard cond entirely — validates the
    vectorized tier in isolation (duplicate account ids across dr/cr lanes
    still occur, exercising the digit scatter-add accumulation)."""
    run_parity(
        6,
        n_batches=8,
        batch_size=40,
        mode="fast",
        chain_rate=0.0,
        two_phase_rate=0.0,
        balancing_rate=0.0,
        limit_account_rate=0.0,
        conflict_rate=0.0,
        invalid_rate=0.3,
    )


def test_lookup_parity():
    oracle, dev = run_parity(7, n_batches=6, batch_size=32, mode="auto", state_every=100)
    gen = WorkloadGenerator(99)
    gen.account_ids = list(oracle.accounts.keys())[:50]
    gen.transfer_ids = list(oracle.transfers.keys())[:50]
    _, ids_a = gen.gen_lookup_batch(40, "accounts")
    _, ids_t = gen.gen_lookup_batch(40, "transfers")
    assert dev.lookup_accounts(ids_a) == oracle.lookup_accounts(ids_a)
    assert dev.lookup_transfers(ids_t) == oracle.lookup_transfers(ids_t)


def test_serial_linked_chain_rollback_exact():
    """Directed: a linked chain that fails mid-way must roll back inserts and
    balance changes (reference: src/state_machine.zig:612-698 scopes)."""
    from tigerbeetle_tpu.types import Account

    oracle = OracleStateMachine()
    dev = DeviceLedger(process=TEST_PROCESS, mode="serial")
    ts = 10_000
    accounts = [Account(id=i, ledger=1, code=1) for i in (1, 2, 3)]
    ts += 3
    assert oracle.execute_dense(Operation.create_accounts, ts, accounts) == \
        dev.execute_dense(Operation.create_accounts, ts, accounts)

    # chain: ok, ok, FAIL(amount=0) -> all three fail; trailing standalone ok.
    transfers = [
        Transfer(id=10, debit_account_id=1, credit_account_id=2, amount=5,
                 ledger=1, code=1, flags=1),
        Transfer(id=11, debit_account_id=2, credit_account_id=3, amount=7,
                 ledger=1, code=1, flags=1),
        Transfer(id=12, debit_account_id=1, credit_account_id=3, amount=0, ledger=1, code=1),
        Transfer(id=13, debit_account_id=1, credit_account_id=2, amount=9, ledger=1, code=1),
    ]
    ts += 4
    dense_o = oracle.execute_dense(Operation.create_transfers, ts, transfers)
    dense_d = dev.execute_dense(Operation.create_transfers, ts, transfers)
    assert dense_o == [1, 1, 18, 0]
    assert dense_d == dense_o
    accounts_d, transfers_d, posted_d = dev.extract()
    assert accounts_d == oracle.accounts
    assert transfers_d == oracle.transfers
    # Rolled-back ids must be absent; id=13 present.
    assert 10 not in transfers_d and 11 not in transfers_d and 12 not in transfers_d
    assert 13 in transfers_d


def test_commit_ts_survives_full_chain_rollback():
    """commit_timestamp advances on at-the-time-ok events and is NOT restored
    by chain rollback (the reference's scopes cover grooves only)."""
    from tigerbeetle_tpu.types import Account

    oracle = OracleStateMachine()
    dev = DeviceLedger(process=TEST_PROCESS, mode="serial")
    ts = 10_000
    accounts = [Account(id=i, ledger=1, code=1) for i in (1, 2)]
    ts += 2
    oracle.execute_dense(Operation.create_accounts, ts, accounts)
    dev.execute_dense(Operation.create_accounts, ts, accounts)
    # The only ok event is rolled back by its chain: commit_ts still moves.
    transfers = [
        Transfer(id=30, debit_account_id=1, credit_account_id=2, amount=5,
                 ledger=1, code=1, flags=1),
        Transfer(id=31, debit_account_id=1, credit_account_id=2, amount=0,
                 ledger=1, code=1),
    ]
    ts += 2
    assert oracle.execute_dense(Operation.create_transfers, ts, transfers) == \
        dev.execute_dense(Operation.create_transfers, ts, transfers) == [1, 18]
    assert dev.commit_timestamp == oracle.commit_timestamp


def test_fast_tier_combined_overflow_hazard():
    """A hazard-free-looking batch mixing pending and posted amounts to one
    account must still hit codes 51/52 (combined dp+dpo overflow, reference:
    src/state_machine.zig:856-861) — the hazard predicate must route it to
    the serial tier rather than silently committing in auto mode."""
    from tigerbeetle_tpu.types import Account, TransferFlags

    oracle = OracleStateMachine()
    dev = DeviceLedger(process=TEST_PROCESS, mode="auto")
    ts = 10_000
    accounts = [Account(id=i, ledger=1, code=1) for i in (1, 2)]
    ts += 2
    oracle.execute_dense(Operation.create_accounts, ts, accounts)
    dev.execute_dense(Operation.create_accounts, ts, accounts)

    big = 1 << 127
    transfers = [
        Transfer(id=40, debit_account_id=1, credit_account_id=2, amount=big,
                 ledger=1, code=1, flags=int(TransferFlags.pending)),
        Transfer(id=41, debit_account_id=1, credit_account_id=2, amount=big,
                 ledger=1, code=1),
    ]
    ts += 2
    dense_o = oracle.execute_dense(Operation.create_transfers, ts, transfers)
    dense_d = dev.execute_dense(Operation.create_transfers, ts, transfers)
    assert dense_o == [0, 51]  # overflows_debits
    assert dense_d == dense_o
    accounts_d, transfers_d, _ = dev.extract()
    assert accounts_d == oracle.accounts
    assert transfers_d == oracle.transfers


def test_capacity_guard():
    import pytest as _pytest

    from tigerbeetle_tpu.constants import ConfigProcess
    from tigerbeetle_tpu.types import Account

    dev = DeviceLedger(
        process=ConfigProcess(account_slots_log2=4, transfer_slots_log2=6), mode="auto"
    )
    accounts = [Account(id=i, ledger=1, code=1) for i in range(1, 16)]
    with _pytest.raises(RuntimeError, match="load-factor"):
        dev.execute_dense(Operation.create_accounts, 100, accounts)


def test_serial_two_phase_post_void_in_batch():
    """Directed: pending + post in the same batch (intra-batch reference)."""
    from tigerbeetle_tpu.types import Account, TransferFlags

    oracle = OracleStateMachine()
    dev = DeviceLedger(process=TEST_PROCESS, mode="serial")
    ts = 10_000
    accounts = [Account(id=i, ledger=1, code=1) for i in (1, 2)]
    ts += 2
    oracle.execute_dense(Operation.create_accounts, ts, accounts)
    dev.execute_dense(Operation.create_accounts, ts, accounts)

    transfers = [
        Transfer(id=20, debit_account_id=1, credit_account_id=2, amount=100,
                 ledger=1, code=1, flags=int(TransferFlags.pending)),
        Transfer(id=21, pending_id=20, amount=60, ledger=0, code=0,
                 flags=int(TransferFlags.post_pending_transfer)),
        Transfer(id=22, pending_id=20, ledger=0, code=0,
                 flags=int(TransferFlags.void_pending_transfer)),  # already posted
    ]
    ts += 3
    dense_o = oracle.execute_dense(Operation.create_transfers, ts, transfers)
    dense_d = dev.execute_dense(Operation.create_transfers, ts, transfers)
    assert dense_o == [0, 0, 33]  # pending_transfer_already_posted
    assert dense_d == dense_o
    accounts_d, transfers_d, posted_d = dev.extract()
    assert accounts_d == oracle.accounts
    assert transfers_d == oracle.transfers
    assert posted_d == oracle.posted
    a1 = accounts_d[1]
    assert a1.debits_posted == 60 and a1.debits_pending == 0
