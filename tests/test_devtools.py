"""Dev tools stay green (reference: tidy.zig + copyhound.zig analogs):
the tree must pass its own lint, and the compute path must not grow new
host-device sync sites without a deliberate re-baseline."""

import pathlib
import subprocess
import sys

ROOT = pathlib.Path(__file__).resolve().parent.parent


def _run(script):
    return subprocess.run([sys.executable, f"scripts/{script}"], cwd=ROOT,
                          capture_output=True, text=True)


def test_tidy_clean():
    r = _run("tidy.py")
    assert r.returncode == 0, r.stdout


def test_copyhound_clean():
    r = _run("copyhound.py")
    assert r.returncode == 0, r.stdout
