"""The vet static-analysis suite (reference: tidy.zig + copyhound.zig
run as build steps).

Two layers:

- fixture tests drive each pass over in-memory toy sources: an
  annotated-correct fixture must pass, and a seeded mutation of the same
  fixture must fail with the expected check id — so the passes are
  tested the way the code they guard is (positive AND negative);
- end-to-end tier-1 tests run `scripts/vet.py` (and the historical
  tidy/copyhound shims) against the real tree and assert green, so a
  regression in any pass — or a new unannotated shared field, sync
  inducer, or nondeterminism source — fails `pytest -q`.
"""

import json
import pathlib
import subprocess
import sys

import pytest

from tigerbeetle_tpu.devtools import (
    CopyhoundPass,
    DeterminismPass,
    RacePass,
    TidyPass,
    VetConfig,
)
from tigerbeetle_tpu.devtools.base import (
    SourceFile,
    apply_baseline,
    load_baseline,
    save_baseline,
)

ROOT = pathlib.Path(__file__).resolve().parent.parent


def cfg(**kw) -> VetConfig:
    return VetConfig(root=ROOT, **kw)


def run_on(pass_, config, **files):
    srcs = [SourceFile(rel, text) for rel, text in sorted(files.items())]
    return pass_.run(srcs, config)


def checks_of(violations):
    return sorted({v.check for v in violations})


# ----------------------------------------------------------------------
# races: thread-ownership lint
# ----------------------------------------------------------------------

RACE_OK = '''\
import threading

class Pipe:
    def __init__(self):
        self.q = Queue()  # vet: handoff
        self._lock = threading.Lock()
        self._count = 0  # vet: guarded-by=_lock
        self._scratch = []  # vet: owner=writer
        self._thread = threading.Thread(target=self._loop, name="writer")
        self._thread.start()

    def _loop(self):
        while True:
            item = self.q.get()
            self._scratch.append(item)
            with self._lock:
                self._count += 1

    def push(self, item):
        self.q.put(item)

    def count(self):
        with self._lock:
            return self._count
'''


def test_races_annotated_fixture_is_clean():
    out = run_on(RacePass(), cfg(race_scan=frozenset({"fix.py"})),
                 **{"fix.py": RACE_OK})
    assert out == [], [v.render() for v in out]


def test_races_unannotated_cross_thread_write_fails():
    src = RACE_OK.replace("self._scratch = []  # vet: owner=writer",
                          "self._scratch = []")
    # push() now also touches the writer thread's list
    src = src.replace("self.q.put(item)",
                      "self.q.put(item)\n        self._scratch.append(item)")
    out = run_on(RacePass(), cfg(race_scan=frozenset({"fix.py"})),
                 **{"fix.py": src})
    assert checks_of(out) == ["unannotated-shared"]
    assert any("_scratch" in v.message for v in out)
    # the violation is baselinable with a stable per-attribute site key
    assert out[0].site == "fix.py::Pipe._scratch"


def test_races_owner_violated_from_event_loop():
    src = RACE_OK.replace(
        "self.q.put(item)",
        "self.q.put(item)\n        self._scratch.append(item)")
    out = run_on(RacePass(), cfg(race_scan=frozenset({"fix.py"})),
                 **{"fix.py": src})
    assert checks_of(out) == ["owner"]
    assert any("main" in v.message and "owner=writer" in v.message
               for v in out)


def test_races_guarded_by_write_outside_lock_fails():
    src = RACE_OK.replace(
        "            with self._lock:\n                self._count += 1",
        "            self._count += 1")
    out = run_on(RacePass(), cfg(race_scan=frozenset({"fix.py"})),
                 **{"fix.py": src})
    assert checks_of(out) == ["guarded-by"]
    assert any("without holding self._lock" in v.message for v in out)


def test_races_guarded_by_unknown_lock_is_bad_annotation():
    src = RACE_OK.replace("guarded-by=_lock", "guarded-by=_no_such_lock")
    out = run_on(RacePass(), cfg(race_scan=frozenset({"fix.py"})),
                 **{"fix.py": src})
    assert "bad-annotation" in checks_of(out)


def test_races_malformed_annotation_is_reported():
    src = RACE_OK.replace("# vet: handoff", "# vet: trust-me")
    out = run_on(RacePass(), cfg(race_scan=frozenset({"fix.py"})),
                 **{"fix.py": src})
    assert "bad-annotation" in checks_of(out)


def test_races_executor_submit_and_callback_infer_threads():
    src = '''\
class Spiller:
    def __init__(self, io):
        self._io = io
        self.jobs = 0

    def kick(self):
        def job():
            self.jobs += 1
        fut = self._io.submit(job)
        fut.add_done_callback(self._done)

    def _done(self, fut):
        self.jobs += 1

    def report(self):
        return self.jobs
'''
    out = run_on(RacePass(), cfg(race_scan=frozenset({"fix.py"})),
                 **{"fix.py": src})
    assert checks_of(out) == ["unannotated-shared"]
    msg = out[0].message
    # the seeded bug crosses the worker (submit), the completing thread
    # (add_done_callback), and the event loop (report)
    assert "worker:_io" in msg and "callback" in msg and "main" in msg


def test_races_lambda_callback_runs_on_the_spawn_thread():
    # review fix: a mutator at the top level of a lambda spawn arg was
    # invisible (generic_visit skipped the body's own node), and must be
    # attributed to the CALLBACK thread, not the enclosing method's
    src = '''\
class Tracker:
    def __init__(self):
        self._pending = set()

    def kick(self, fut):
        self._pending.add(fut)
        fut.add_done_callback(lambda f: self._pending.discard(f))
'''
    out = run_on(RacePass(), cfg(race_scan=frozenset({"fix.py"})),
                 **{"fix.py": src})
    assert checks_of(out) == ["unannotated-shared"]
    assert "callback" in out[0].message and "main" in out[0].message
    # worker-side-only mutation via a submitted lambda is NOT flagged as
    # shared with the enclosing thread (the body never runs there)
    src2 = '''\
class Logger:
    def __init__(self, io):
        self._io = io

    def kick(self):
        self._io.submit(lambda: self._lines.append(1))
'''
    out = run_on(RacePass(), cfg(race_scan=frozenset({"fix.py"})),
                 **{"fix.py": src2})
    assert out == []


def test_races_bare_name_thread_spawn_is_seen():
    # review fix: `from threading import Thread` spawns with a bare
    # Name call, which used to bypass spawn recognition entirely — the
    # unannotated cross-thread write below came back with ZERO
    # violations because every method collapsed onto "main"
    src = RACE_OK.replace("import threading\n",
                          "from threading import Thread, Lock\n")
    src = src.replace("threading.", "")
    out = run_on(RacePass(), cfg(race_scan=frozenset({"fix.py"})),
                 **{"fix.py": src})
    assert out == [], [v.render() for v in out]
    bad = src.replace("self._scratch = []  # vet: owner=writer",
                      "self._scratch = []")
    bad = bad.replace("self.q.put(item)",
                      "self.q.put(item)\n        self._scratch.append(item)")
    out = run_on(RacePass(), cfg(race_scan=frozenset({"fix.py"})),
                 **{"fix.py": bad})
    assert checks_of(out) == ["unannotated-shared"]
    assert any("_scratch" in v.message for v in out)
    # an ALIASED from-import must not evade either
    aliased = bad.replace("from threading import Thread, Lock",
                          "from threading import Lock\n"
                          "from threading import Thread as _T")
    aliased = aliased.replace("Thread(target=", "_T(target=")
    out = run_on(RacePass(), cfg(race_scan=frozenset({"fix.py"})),
                 **{"fix.py": aliased})
    assert checks_of(out) == ["unannotated-shared"], \
        [v.render() for v in out]


def test_races_thread_spawned_from_init_is_not_construction():
    # review fix: the __init__ construction exemption also swallowed
    # nested functions SPAWNED from __init__ — `def loop(): ...;
    # Thread(target=loop)` in a constructor runs on the spawned thread
    # later, and its cross-thread accesses were dropped entirely
    src = '''\
from threading import Thread

class Pump:
    def __init__(self):
        self._buf = []

        def loop():
            self._buf.append(1)

        Thread(target=loop, name="pump").start()

    def drain(self):
        return list(self._buf)
'''
    out = run_on(RacePass(), cfg(race_scan=frozenset({"fix.py"})),
                 **{"fix.py": src})
    assert checks_of(out) == ["unannotated-shared"]
    assert any("_buf" in v.message for v in out), \
        [v.render() for v in out]


def test_races_submit_data_args_are_not_spawn_targets():
    # review fix: every positional submit() arg used to be treated as
    # a potential spawn target, so a DATA argument whose name collides
    # with a method moved that method onto the worker thread and fired
    # a spurious unannotated-shared
    src = '''\
class Box:
    def __init__(self):
        self._ex = Pool()
        self._n = 0

    def _job(self, arg):
        pass

    def kick(self):
        flush = 1
        self._ex.submit(self._job, flush)

    def flush(self):
        self._n += 1

    def read(self):
        return self._n
'''
    out = run_on(RacePass(), cfg(race_scan=frozenset({"fix.py"})),
                 **{"fix.py": src})
    assert out == [], [v.render() for v in out]


def test_races_augassign_rhs_attribute_read_is_seen():
    # review fix: visit_AugAssign generic_visit'ed the RHS, dropping a
    # top-level self-attribute read — `self.total += self.base` on the
    # worker never recorded the base read, silencing a real race
    src = RACE_OK.replace(
        "            self._scratch.append(item)",
        "            self._scratch.append(item)\n"
        "            self._total += self._base",
    )
    src = src.replace(
        "    def push(self, item):",
        "    def rebase(self, b):\n"
        "        self._base = b\n\n"
        "    def push(self, item):",
    )
    out = run_on(RacePass(), cfg(race_scan=frozenset({"fix.py"})),
                 **{"fix.py": src})
    assert "unannotated-shared" in checks_of(out)
    assert any("_base" in v.message for v in out), \
        [v.render() for v in out]


def test_races_positional_thread_target_is_seen():
    # review fix: spawn recognition only read the `target=` keyword —
    # threading.Thread(None, self._loop) (the positional signature) got
    # zero race coverage silently
    src = '''\
import threading

class Tail:
    def __init__(self):
        self._items = []
        threading.Thread(None, self._loop).start()

    def _loop(self):
        self._items.append(1)

    def drain(self):
        return list(self._items)
'''
    out = run_on(RacePass(), cfg(race_scan=frozenset({"fix.py"})),
                 **{"fix.py": src})
    assert checks_of(out) == ["unannotated-shared"]
    assert any("_items" in v.message for v in out), \
        [v.render() for v in out]


def test_races_augassign_index_read_is_seen():
    # review fix: `self.buf[self.head] += 1` recorded the buf write but
    # never the head READ, so a cross-thread unannotated index attr was
    # invisible when only touched inside augmented-subscript indices
    src = RACE_OK.replace(
        "            self._scratch.append(item)",
        "            self._scratch.append(item)\n"
        "            self._slots[self._head] += 1",
    )
    src = src.replace(
        "    def push(self, item):",
        "    def reset(self):\n"
        "        self._head = 0\n\n"
        "    def push(self, item):",
    )
    out = run_on(RacePass(), cfg(race_scan=frozenset({"fix.py"})),
                 **{"fix.py": src})
    assert "unannotated-shared" in checks_of(out)
    assert any("_head" in v.message for v in out), \
        [v.render() for v in out]


def test_races_files_outside_scan_set_are_ignored():
    src = RACE_OK.replace("self._scratch = []  # vet: owner=writer",
                          "self._scratch = []")
    out = run_on(RacePass(), cfg(race_scan=frozenset({"other.py"})),
                 **{"fix.py": src})
    assert out == []


# ----------------------------------------------------------------------
# determinism: sim-reachable code stays seed-deterministic
# ----------------------------------------------------------------------

def det_cfg(**kw):
    kw.setdefault("sim_roots", ("simroot.py",))
    kw.setdefault("prod_only", {})
    kw.setdefault("clock_seam", frozenset())
    kw.setdefault("executor_seam", {})
    return cfg(**kw)


SIM_ROOT = "import simmod\n"


def test_determinism_clean_module_passes():
    out = run_on(DeterminismPass(), det_cfg(), **{
        "simroot.py": SIM_ROOT,
        "simmod.py": "def step(rng):\n    return rng.random()\n",
    })
    assert out == []


@pytest.mark.parametrize("body,check", [
    ("import time\n\ndef now():\n    return time.time()\n", "wall-clock"),
    ("import time as _t\n\ndef now():\n    return _t.perf_counter()\n",
     "wall-clock"),
    ("import random\n\ndef roll():\n    return random.random()\n",
     "unseeded-random"),
    ("import random\n\ndef rng():\n    return random.Random()\n",
     "unseeded-random"),
    ("import os\n\ndef salt():\n    return os.urandom(8)\n",
     "unseeded-random"),
    ("def drain(ids):\n    seen = set(ids)\n"
     "    return [i for i in seen]\n", "set-iteration"),
    ("import threading\n\ndef spawn(fn):\n"
     "    threading.Thread(target=fn).start()\n", "direct-thread"),
])
def test_determinism_rejects_nondeterminism_sources(body, check):
    out = run_on(DeterminismPass(), det_cfg(), **{
        "simroot.py": SIM_ROOT, "simmod.py": body,
    })
    assert checks_of(out) == [check], [v.render() for v in out]


@pytest.mark.parametrize("body,check", [
    ("from time import perf_counter\n\ndef now():\n"
     "    return perf_counter()\n", "wall-clock"),
    ("from time import perf_counter_ns as pc\n\ndef now():\n"
     "    return pc()\n", "wall-clock"),
    ("from random import random\n\ndef roll():\n    return random()\n",
     "unseeded-random"),
    ("from random import Random\n\ndef rng():\n    return Random()\n",
     "unseeded-random"),
    ("from os import urandom\n\ndef salt():\n    return urandom(8)\n",
     "unseeded-random"),
    ("from uuid import uuid4 as mkid\n\ndef new_id():\n"
     "    return mkid()\n", "unseeded-random"),
])
def test_determinism_rejects_from_imported_sources(body, check):
    # review fix: from-imports bind bare names, which the dotted
    # two-part checks never matched — one import-style change used to
    # silently defeat the lint
    out = run_on(DeterminismPass(), det_cfg(), **{
        "simroot.py": SIM_ROOT, "simmod.py": body,
    })
    assert checks_of(out) == [check], [v.render() for v in out]


def test_determinism_from_imported_seeded_random_is_fine():
    out = run_on(DeterminismPass(), det_cfg(), **{
        "simroot.py": SIM_ROOT,
        "simmod.py": "from random import Random\n\ndef rng(seed):\n"
                     "    return Random(seed)\n",
    })
    assert out == []


def test_determinism_seeded_random_is_fine():
    out = run_on(DeterminismPass(), det_cfg(), **{
        "simroot.py": SIM_ROOT,
        "simmod.py": "import random\n\ndef rng(seed):\n"
                     "    return random.Random(seed)\n",
    })
    assert out == []


def test_determinism_set_locals_are_function_scoped():
    # review fix: the set-typed-name map was file-global, so a set
    # local in one function flagged iteration over an unrelated
    # like-named list local in another function
    out = run_on(DeterminismPass(), det_cfg(), **{
        "simroot.py": SIM_ROOT,
        "simmod.py": "def a():\n"
                     "    pending = set()\n"
                     "    return sorted(pending)\n\n"
                     "def b(items):\n"
                     "    pending = list(items)\n"
                     "    return [p for p in pending]\n",
    })
    assert out == [], [v.render() for v in out]


def test_determinism_set_attributes_stay_file_wide():
    # `self.x` keys are attributes, not locals — assigned a set in
    # __init__, iterating them in another method must still flag
    out = run_on(DeterminismPass(), det_cfg(), **{
        "simroot.py": SIM_ROOT,
        "simmod.py": "class T:\n"
                     "    def __init__(self):\n"
                     "        self.ids = set()\n\n"
                     "    def drain(self):\n"
                     "        return [i for i in self.ids]\n",
    })
    assert checks_of(out) == ["set-iteration"], \
        [v.render() for v in out]


def test_determinism_sorted_set_iteration_is_fine():
    out = run_on(DeterminismPass(), det_cfg(), **{
        "simroot.py": SIM_ROOT,
        "simmod.py": "def drain(ids):\n    seen = set(ids)\n"
                     "    return [i for i in sorted(seen)]\n",
    })
    assert out == []


def test_determinism_roots_are_themselves_in_scope():
    # review fix: the closure anchors on the roots, it does not exempt
    # them — a wall clock in the VOPR driver itself must flag
    out = run_on(DeterminismPass(), det_cfg(), **{
        "simroot.py": "import time\n\ndef main():\n"
                      "    return time.time()\n",
    })
    assert checks_of(out) == ["wall-clock"]


def test_determinism_scope_is_the_import_closure():
    # same wall-clock body, but the module is never imported by a root
    out = run_on(DeterminismPass(), det_cfg(), **{
        "simroot.py": "X = 1\n",
        "simmod.py": "import time\n\ndef now():\n    return time.time()\n",
    })
    assert out == []


def test_determinism_closure_follows_relative_imports():
    # review fix: relative imports (level > 0) used to be dropped from
    # the closure, silently unscanning the imported subtree — both from
    # a regular module (`pkg/root.py`) and from a package __init__,
    # whose first dot level is the package itself
    files = {
        "pkg/__init__.py": "from . import depmod\n",
        "pkg/root.py": "from . import simmod\nfrom .other import thing\n",
        "pkg/simmod.py": "import time\n\ndef a():\n    return time.time()\n",
        "pkg/other.py": "import time\n\ndef b():\n"
                        "    return time.monotonic()\n",
        "pkg/depmod.py": "import time\n\ndef c():\n"
                         "    return time.perf_counter()\n",
    }
    out = run_on(DeterminismPass(), det_cfg(sim_roots=("pkg/root.py",)),
                 **files)
    assert checks_of(out) == ["wall-clock"]
    assert {v.file for v in out} == {
        "pkg/simmod.py", "pkg/other.py", "pkg/depmod.py"
    }, [v.render() for v in out]


def test_determinism_closure_includes_ancestor_packages():
    # review fix: `import pkg.sub.mod` executes pkg/__init__ and
    # pkg/sub/__init__ at runtime; those used to be absent from the
    # closure, so a wall clock in a package __init__ passed silently
    files = {
        "pkg/__init__.py": "import time\n\ndef boot():\n"
                           "    return time.time()\n",
        "pkg/sub/__init__.py": "",
        "pkg/sub/mod.py": "X = 1\n",
        "root.py": "import pkg.sub.mod\n",
    }
    out = run_on(DeterminismPass(), det_cfg(sim_roots=("root.py",)),
                 **files)
    assert checks_of(out) == ["wall-clock"]
    assert {v.file for v in out} == {"pkg/__init__.py"}, \
        [v.render() for v in out]


def test_determinism_clock_seam_parameter_named_time_is_fine():
    # review fix: the module-alias sets were unconditionally seeded
    # with "time"/"random", so passing the DeterministicTime seam as a
    # parameter named `time` (the natural name) was misread as the
    # stdlib module
    out = run_on(DeterminismPass(), det_cfg(), **{
        "simroot.py": SIM_ROOT,
        "simmod.py": "def run(time):\n    return time.monotonic()\n",
    })
    assert out == []


def test_determinism_prod_only_allowlist_and_clock_seam_skip():
    files = {
        "simroot.py": "import simmod\nimport clockmod\n",
        "simmod.py": "import time\n\ndef now():\n    return time.time()\n",
        "clockmod.py": "import time\n\ndef now():\n"
                       "    return time.monotonic()\n",
    }
    out = run_on(DeterminismPass(), det_cfg(
        prod_only={"simmod.py": "prod sink, sim never constructs it"},
        clock_seam=frozenset({"clockmod.py"}),
    ), **files)
    assert out == []
    # without the allowlist both modules fail
    out = run_on(DeterminismPass(), det_cfg(), **files)
    assert len(out) == 2


def test_determinism_executor_seam_may_construct_threads():
    files = {
        "simroot.py": SIM_ROOT,
        "simmod.py": "import threading\n\ndef spawn(fn):\n"
                     "    threading.Thread(target=fn).start()\n",
    }
    out = run_on(DeterminismPass(), det_cfg(
        executor_seam={"simmod.py": "IS the seam"}), **files)
    assert out == []


# ----------------------------------------------------------------------
# copyhound v2: host<->device sync inducers
# ----------------------------------------------------------------------

def ch_cfg():
    return cfg(copyhound_dirs=("pkg/",), kernel_holders=("self.kernels",))


def test_copyhound_clean_device_code_passes():
    out = run_on(CopyhoundPass(), ch_cfg(), **{
        "pkg/k.py": "import jax.numpy as jnp\n\n"
                    "def step(x):\n    return jnp.cumsum(x)\n",
    })
    assert out == []


@pytest.mark.parametrize("body,check", [
    # explicit sync calls, by name
    ("def pull(x):\n    return np.asarray(x)\n", "asarray"),
    ("def fence(x):\n    x.block_until_ready()\n", "block_until_ready"),
    ("def pull(x):\n    return jax.device_get(x)\n", "device_get"),
    ("def wire(x):\n    return x.tobytes()\n", "tobytes"),
    ("def one(x):\n    return x.item()\n", "item"),
    # implicit inducers via the taint walk
    ("import jax.numpy as jnp\n\ndef total(x):\n"
     "    t = jnp.sum(x)\n    return float(t)\n", "coerce"),
    ("import jax.numpy as jnp\nimport numpy as np\n\ndef mix(x):\n"
     "    t = jnp.cumsum(x)\n    return np.maximum(t, 0)\n",
     "np-on-device"),
    # review fix: keyword-passed device values induce the transfer too
    ("import jax.numpy as jnp\nimport numpy as np\n\ndef kw(x):\n"
     "    t = jnp.cumsum(x)\n    return np.sum(a=t)\n",
     "np-on-device"),
    ("import jax.numpy as jnp\n\ndef log(x):\n"
     "    t = jnp.sum(x)\n    return f'total={t}'\n", "fstring"),
    # kernel-bundle results are device values too
    ("class Led:\n    def go(self, x):\n"
     "        r = self.kernels.commit(x)\n        return int(r)\n",
     "coerce"),
])
def test_copyhound_catches_sync_inducers(body, check):
    out = run_on(CopyhoundPass(), ch_cfg(), **{"pkg/k.py": body})
    assert check in checks_of(out), [v.render() for v in out]
    assert all(v.site == f"pkg/k.py::{v.check}" for v in out)


def test_copyhound_asarray_result_is_host_side():
    # np.asarray IS the sync (one hit); using its result is clean — no
    # cascading coerce/np-on-device/fstring hits downstream
    out = run_on(CopyhoundPass(), ch_cfg(), **{
        "pkg/k.py": "import jax.numpy as jnp\nimport numpy as np\n\n"
                    "def drain(x):\n"
                    "    t = jnp.cumsum(x)\n"
                    "    h = np.asarray(t)\n"
                    "    return float(h), np.maximum(h, 0), f'{h}'\n",
    })
    assert checks_of(out) == ["asarray"]
    assert len(out) == 1


def test_copyhound_jnp_asarray_result_stays_device_side():
    # review fix: the _UNTAINTING leaf check fired before the jnp root
    # check, so jnp.asarray — h2d STAGING, its result is a device
    # array — was treated like np.asarray's host materialization and a
    # downstream accidental d2h rode under the baselined asarray why
    out = run_on(CopyhoundPass(), ch_cfg(), **{
        "pkg/k.py": "import jax.numpy as jnp\n\n"
                    "def stage(host_buf):\n"
                    "    t = jnp.asarray(host_buf)\n"
                    "    return float(t)\n",
    })
    # the staging upload counts under its OWN site key (asarray-h2d),
    # so swapping it for a real np.asarray d2h can't hide in the count
    assert checks_of(out) == ["asarray-h2d", "coerce"], \
        [v.render() for v in out]


def test_copyhound_sees_module_and_class_scope():
    # review fix: v1's whole-tree walk caught module-level / class-body
    # sync calls; v2's per-function taint walk must not narrow that
    out = run_on(CopyhoundPass(), ch_cfg(), **{
        "pkg/k.py": "import numpy as np\n\n"
                    "LUT = np.asarray(range(8))\n\n"
                    "class T:\n"
                    "    TABLE = np.asarray(range(4))\n",
    })
    assert checks_of(out) == ["asarray"]
    assert len(out) == 2


def test_copyhound_scan_covers_the_commit_path_dirs():
    config = cfg()
    for d in ("ops", "models", "parallel", "vsr", "lsm", "cdc",
              "ingress", "io"):
        assert f"tigerbeetle_tpu/{d}/" in config.copyhound_dirs


def test_copyhound_ignores_files_off_the_compute_path():
    out = run_on(CopyhoundPass(), ch_cfg(), **{
        "other/k.py": "def pull(x):\n    return np.asarray(x)\n",
    })
    assert out == []


# ----------------------------------------------------------------------
# tidy: source form + named noqa
# ----------------------------------------------------------------------

def test_tidy_named_noqa_suppresses_and_bare_noqa_fails():
    out = run_on(TidyPass(), cfg(), **{
        "tigerbeetle_tpu/x.py":
            "import os  # noqa: unused-import\nX = 1\n",
    })
    assert out == []
    bare = "import os  # noq" + "a\nX = 1\n"  # split: tidy scans THIS file
    out = run_on(TidyPass(), cfg(), **{"tigerbeetle_tpu/x.py": bare})
    # the bare marker is its own violation AND suppresses nothing
    assert checks_of(out) == ["bare-noqa", "unused-import"]


def test_tidy_noqa_naming_a_different_check_does_not_suppress():
    out = run_on(TidyPass(), cfg(), **{
        "tigerbeetle_tpu/x.py":
            "import os  # noqa: library-print\nX = 1\n",
    })
    assert checks_of(out) == ["unused-import"]


def test_tidy_source_form_checks():
    out = run_on(TidyPass(), cfg(), **{
        "tigerbeetle_tpu/x.py":
            "X = 1 \nY = '\t'\nZ = '" + "z" * 120 + "'\n",
    })
    assert checks_of(out) == ["line-length", "tab", "trailing-whitespace"]


def test_tidy_library_print_policy():
    body = "def f():\n    print('hi')\n"
    out = run_on(TidyPass(), cfg(), **{"tigerbeetle_tpu/x.py": body})
    assert checks_of(out) == ["library-print"]
    # user-facing surfaces and non-library code may print
    for rel in ("tigerbeetle_tpu/cli.py", "scripts/x.py", "tests/x.py"):
        out = run_on(TidyPass(), cfg(), **{rel: body})
        assert out == [], rel


# ----------------------------------------------------------------------
# closed baselines
# ----------------------------------------------------------------------

def V(site, n=1):
    from tigerbeetle_tpu.devtools.base import Violation

    return [
        Violation("f.py", i + 1, "p", "c", "msg", site=site)
        for i in range(n)
    ]


def test_baseline_suppresses_explained_matching_sites():
    base = {"f.py::c": {"site": "f.py::c", "count": 2, "why": "known"}}
    assert apply_baseline("p", V("f.py::c", 2), base, "b.json") == []


def test_baseline_empty_why_fails():
    base = {"f.py::c": {"site": "f.py::c", "count": 1, "why": ""}}
    out = apply_baseline("p", V("f.py::c", 1), base, "b.json")
    assert [v.check for v in out] == ["baseline-why"]


def test_baseline_new_site_and_excess_count_fail():
    out = apply_baseline("p", V("f.py::c", 1), {}, "b.json")
    assert [v.check for v in out] == ["c"]
    base = {"f.py::c": {"site": "f.py::c", "count": 1, "why": "known"}}
    out = apply_baseline("p", V("f.py::c", 3), base, "b.json")
    assert [v.check for v in out] == ["c", "c"]  # only the excess


def test_baseline_is_closed_in_both_directions():
    base = {
        "f.py::c": {"site": "f.py::c", "count": 2, "why": "known"},
        "gone.py::c": {"site": "gone.py::c", "count": 1, "why": "known"},
    }
    out = apply_baseline("p", V("f.py::c", 1), base, "b.json")
    # shrunk count AND vanished site both report as stale
    assert [v.check for v in out] == ["baseline-stale", "baseline-stale"]
    assert any("gone.py::c" in v.message for v in out)


def test_baseline_update_keeps_whys_and_flags_new_sites(tmp_path):
    path = tmp_path / "b.json"
    old = {"a::x": {"site": "a::x", "count": 1, "why": "justified"}}
    unexplained = save_baseline(path, {"a::x": 2, "b::y": 1}, old)
    assert unexplained == 1  # b::y needs a human why before green
    loaded = load_baseline(path)
    assert loaded["a::x"]["why"] == "justified"
    assert loaded["a::x"]["count"] == 2
    assert loaded["b::y"]["why"] == ""


def test_baseline_v1_schema_lifts_with_empty_whys(tmp_path):
    path = tmp_path / "b.json"
    path.write_text(json.dumps({"a.py": {"asarray": 3}}))
    loaded = load_baseline(path)
    assert loaded == {
        "a.py::asarray": {"site": "a.py::asarray", "count": 3, "why": ""},
    }


def test_repo_baselines_all_carry_whys():
    for name in ("copyhound_baseline.json", "determinism_baseline.json"):
        raw = json.loads((ROOT / "scripts" / name).read_text())
        assert raw["version"] == 2, name
        for e in raw["entries"]:
            assert e["why"].strip(), f"{name}: {e['site']} has no why"


# ----------------------------------------------------------------------
# end-to-end: the real tree stays green (tier-1)
# ----------------------------------------------------------------------

def _run(script, *args):
    return subprocess.run(
        [sys.executable, f"scripts/{script}", *args], cwd=ROOT,
        capture_output=True, text=True,
    )


def test_vet_whole_tree_green():
    """All passes over the real tree: a new unannotated shared field,
    sync inducer, nondeterminism source, or stale/unexplained baseline
    entry fails tier-1 here."""
    r = _run("vet.py")
    assert r.returncode == 0, r.stdout + r.stderr
    assert "vet: clean" in r.stdout


def test_vet_pass_selection_and_explain():
    r = _run("vet.py", "--pass", "tidy,races")
    assert r.returncode == 0, r.stdout + r.stderr
    assert "vet: clean (tidy, races)" in r.stdout
    r = _run("vet.py", "--explain", "races")
    assert r.returncode == 0
    assert "owner" in r.stdout and "guarded-by" in r.stdout
    r = _run("vet.py", "--explain", "copyhound/coerce")
    assert r.returncode == 0 and "coerce" in r.stdout
    r = _run("vet.py", "--explain", "copyhound/nope")
    assert r.returncode == 1


def test_vet_unknown_pass_name_is_a_clean_error():
    # review fix: a typo'd --pass used to die with an AssertionError
    # traceback (and a KeyError under python -O)
    r = _run("vet.py", "--pass", "race")
    assert r.returncode == 1
    assert "unknown pass" in (r.stdout + r.stderr)
    assert "Traceback" not in r.stderr, r.stderr


def test_tidy_shim_clean():
    r = _run("tidy.py")
    assert r.returncode == 0, r.stdout


def test_copyhound_shim_clean():
    r = _run("copyhound.py")
    assert r.returncode == 0, r.stdout
