"""HBM↔LSM spill scheduler: bounded-memory parity (models/spill.py).

TEST_PROCESS's transfer table limit is 2^12 / 2 = 2048 rows; these workloads
submit several times that, forcing repeated spill cycles, while the
workload's conflict/two-phase knobs keep referencing long-spilled ids — the
reload (prefetch) path. Every batch's result codes and the merged
extract()/lookup surfaces must stay bit-exact against the oracle, which
never evicts anything (reference contract: src/lsm/groove.zig:602-760 —
the store is logically unbounded; residency is an implementation detail).
"""

import numpy as np
import pytest

from tigerbeetle_tpu.constants import TEST_CLUSTER, TEST_PROCESS
from tigerbeetle_tpu.io.storage import MemoryStorage, ZoneLayout
from tigerbeetle_tpu.lsm.grid import Grid
from tigerbeetle_tpu.lsm.groove import Forest
from tigerbeetle_tpu.models.ledger import DeviceLedger
from tigerbeetle_tpu.models.oracle import OracleStateMachine
from tigerbeetle_tpu.models.spill import SpillManager
from tigerbeetle_tpu.testing.workload import WorkloadGenerator

LAYOUT = ZoneLayout(TEST_CLUSTER, grid_size=96 * 1024 * 1024)


def _forest(storage=None):
    storage = storage or MemoryStorage(LAYOUT)
    return storage, Forest(
        Grid(storage, offset=0, block_count=640, cache_blocks=64)
    )


def run_spill_parity(seed, n_transfer_batches=60, batch_size=72,
                     state_every=10, **wl_kwargs):
    oracle = OracleStateMachine()
    storage, forest = _forest()
    dev = DeviceLedger(process=TEST_PROCESS, mode="auto", forest=forest)
    # High apply-rate knobs (single ledger, few invalids) so the store
    # actually FILLS past the 2048-row limit; the residual conflict and
    # two-phase rates keep referencing long-spilled ids (the reload path).
    knobs = dict(
        ledgers=(1,),
        invalid_rate=0.03,
        conflict_rate=0.06,
        chain_rate=0.02,
        two_phase_rate=0.15,
        balancing_rate=0.05,
        limit_account_rate=0.05,
    )
    knobs.update(wl_kwargs)
    gen = WorkloadGenerator(seed, **knobs)
    ts = 1_000_000_000

    def run_batch(op, events, b):
        nonlocal ts
        ts += len(events)
        dense_o = oracle.execute_dense(op, ts, events)
        dense_d = dev.execute_dense(op, ts, events)
        if dense_d != dense_o:
            diffs = [
                (i, o, d)
                for i, (o, d) in enumerate(zip(dense_o, dense_d))
                if o != d
            ]
            raise AssertionError(
                f"batch {b} ({op.name}): (idx, oracle, dev) {diffs[:10]}"
            )

    # A bounded account population (the account table does not spill) with
    # an unbounded transfer history — the reference benchmark's shape.
    for b in range(4):
        op, events = gen.gen_accounts_batch(40)
        run_batch(op, events, b)
    for b in range(n_transfer_batches):
        op, events = gen.gen_transfers_batch(batch_size)
        run_batch(op, events, 4 + b)
        if b % state_every == state_every - 1:
            accounts, transfers, posted = dev.extract()
            assert accounts == oracle.accounts, f"batch {b}: accounts diverged"
            assert transfers == oracle.transfers, f"batch {b}: transfers diverged"
            assert posted == oracle.posted, f"batch {b}: posted diverged"
    return oracle, dev, storage


@pytest.mark.parametrize("seed", [11, 12])
def test_spill_parity(seed):
    oracle, dev, _ = run_spill_parity(seed)
    assert dev.spill.stats["cycles"] >= 1, "workload never spilled"
    assert dev.spill.stats["reloaded"] >= 1, "workload never reloaded"
    assert len(dev.spill.spilled) > 0
    # final full-state parity (HBM + LSM merged)
    accounts, transfers, posted = dev.extract()
    assert accounts == oracle.accounts
    assert transfers == oracle.transfers
    assert posted == oracle.posted


def test_spill_lookup_parity():
    """Lookups must see spilled rows (LSM fallback) and HBM rows alike."""
    oracle, dev, _ = run_spill_parity(13, n_transfer_batches=52)
    assert dev.spill.stats["cycles"] >= 1
    ids = sorted(oracle.transfers.keys())
    rng = np.random.default_rng(0)
    sample = [ids[i] for i in rng.choice(len(ids), size=60, replace=False)]
    sample += [9999999999]  # a miss
    assert dev.lookup_transfers(sample) == oracle.lookup_transfers(sample)
    # some of the sample must actually have come from the LSM store
    assert any(i in dev.spill.spilled for i in sample)


def test_spill_store_restore():
    """checkpoint_meta/restore round-trips the LSM manifest + spilled-id set
    through a fresh Grid/Forest over the same storage (the restart path the
    superblock checkpoint hook uses)."""
    oracle, dev, storage = run_spill_parity(14, n_transfer_batches=52)
    meta = dev.spill.checkpoint_meta()
    _, forest2 = _forest(storage)
    sm2 = SpillManager(dev, forest2)
    sm2.restore(meta)
    dev.spill = sm2
    accounts, transfers, posted = dev.extract()
    assert transfers == oracle.transfers
    assert posted == oracle.posted


def test_spill_checkpoint_survives_later_churn():
    """A checkpointed manifest must stay readable after LATER spill cycles
    compact and release blocks: releases stage until the next checkpoint
    (crash-restore to the old checkpoint must find its blocks intact)."""
    oracle, dev, storage = run_spill_parity(17, n_transfer_batches=30)
    meta = dev.spill.checkpoint_meta()
    want = {
        id_: dev.spill._fetch(id_) for id_ in sorted(dev.spill.spilled)
    }
    # keep running: more cycles, flushes, compactions (block churn)
    gen = WorkloadGenerator(18, ledgers=(1,), invalid_rate=0.0,
                            conflict_rate=0.0, chain_rate=0.0,
                            two_phase_rate=0.0, balancing_rate=0.0)
    gen.next_id = 1_000_000  # disjoint id space from the first generator
    gen.account_ids = list(oracle.accounts.keys())[:20]
    ts = 3_000_000_000
    for b in range(45):
        op, events = gen.gen_transfers_batch(72)
        ts += len(events)
        dev.execute_dense(op, ts, events)
    assert dev.spill.stats["cycles"] >= 2
    # restore the OLD checkpoint into a fresh forest over the same storage:
    # every spilled row it recorded must still read back bit-exact
    _, forest2 = _forest(storage)
    sm2 = SpillManager(dev, forest2)
    sm2.restore(meta)
    for id_, (row, ful) in want.items():
        got = sm2._fetch(id_)
        assert got == (row, ful), id_


def test_spill_durable_restart():
    """The full durable path: DurableLedger with a forest block area in the
    layout — WAL + superblock checkpoints carry the spill meta; a restart
    replays to bit-exact state including the spilled tail."""
    from tigerbeetle_tpu.vsr.durable import DurableLedger, format_data_file

    layout = ZoneLayout(TEST_CLUSTER, grid_size=96 * 1024 * 1024,
                        forest_blocks=448)
    storage = MemoryStorage(layout)
    format_data_file(storage, TEST_CLUSTER)
    d1 = DurableLedger(storage, TEST_CLUSTER, TEST_PROCESS)
    d1.open()
    assert d1.forest is not None and d1.ledger.spill is not None

    oracle = OracleStateMachine()
    gen = WorkloadGenerator(19, ledgers=(1,), invalid_rate=0.03,
                            conflict_rate=0.06, chain_rate=0.02,
                            two_phase_rate=0.15, balancing_rate=0.05,
                            limit_account_rate=0.05)
    import tigerbeetle_tpu.types as types
    from tigerbeetle_tpu.types import Operation

    def submit(op, events):
        to_np = (types.accounts_to_np if op == Operation.create_accounts
                 else types.transfers_to_np)
        body = to_np(events).tobytes()
        d1.submit(op, body)
        oracle.prepare(op, len(events))
        oracle.execute_dense(op, d1.sm.prepare_timestamp, events)

    for _ in range(3):
        op, events = gen.gen_accounts_batch(40)
        submit(op, events)
    for b in range(62):
        op, events = gen.gen_transfers_batch(72)
        submit(op, events)
    assert d1.ledger.spill.stats["cycles"] >= 1
    assert d1.checkpoint_op > 0, "no checkpoint happened (WAL wrap expected)"

    # crash: new process over the same storage
    d2 = DurableLedger(storage, TEST_CLUSTER, TEST_PROCESS)
    d2.open()
    a2, t2, p2 = d2.ledger.extract()
    assert a2 == oracle.accounts
    assert t2 == oracle.transfers
    assert p2 == oracle.posted


def test_forced_serial_spill_parity():
    """The exact serial tier must also see reloaded rows (its probes hit the
    same HBM tables)."""
    oracle, dev, _ = run_spill_parity(
        15, n_transfer_batches=52, batch_size=72, state_every=8
    )
    # exercised implicitly by hazard routing; force a final serial batch
    # that references old (spilled) ids via duplicates
    gen = WorkloadGenerator(16)
    gen.account_ids = list(oracle.accounts.keys())[:20]
    gen.transfer_ids = sorted(dev.spill.spilled)[:30]
    gen.pending_ids = [
        t.id for t in oracle.transfers.values() if t.flags & 2
    ][:10]
    op, events = gen.gen_transfers_batch(48)
    ts = 2_000_000_000
    dense_o = oracle.execute_dense(op, ts, events)
    dev.mode = "serial"
    dense_d = dev.execute_dense(op, ts, events)
    assert dense_d == dense_o


def test_spill_overlap_pipeline_smoke():
    """The bench's overlapped spill pipeline in miniature (CI smoke for
    spill-path regressions that otherwise surface only in the 147s
    cfg_spill bench stage): a window of batches stays in flight, batch
    g+1's referenced-spilled rows prefetch on the IO worker while batch g
    commits, drains lag dispatch — with a tiny keep_frac so cycles fire
    every few batches. Asserts the overlap machinery actually ran
    (prefetches consumed, batched multi-lookups amortized) AND full-state
    parity against the oracle."""
    from tigerbeetle_tpu.types import Operation

    oracle = OracleStateMachine()
    _, forest = _forest()
    # TEST_PROCESS geometry (kernel compiles shared with the other spill
    # tests in this file); the tiny keep_frac + enough batches force
    # multiple cycles within the 2048-row budget
    dev = DeviceLedger(process=TEST_PROCESS, mode="auto", forest=forest,
                       spill_keep_frac=0.2)
    knobs = dict(ledgers=(1,), invalid_rate=0.0, conflict_rate=0.1,
                 chain_rate=0.0, two_phase_rate=0.2, balancing_rate=0.0,
                 limit_account_rate=0.0)
    gen = WorkloadGenerator(21, **knobs)
    ts = 1_000_000_000
    for _ in range(2):
        op, events = gen.gen_accounts_batch(40)
        ts += len(events)
        oracle.execute_dense(op, ts, events)
        dev.execute_dense(op, ts, events)
    from tigerbeetle_tpu import types as tb_types

    batches = []
    for _ in range(60):
        op, events = gen.gen_transfers_batch(96)
        batches.append((events, tb_types.transfers_to_np(events)))
    window = []
    oracle_dense = []
    for g, (events, arr) in enumerate(batches):
        ts += len(arr)
        oracle_dense.append(oracle.execute_dense(
            Operation.create_transfers, ts, events
        ))
        window.append((g, dev.execute_async(
            Operation.create_transfers, ts, arr
        )))
        if g + 1 < len(batches):
            dev.spill.prefetch_async(batches[g + 1][1])
        while len(window) > 3:  # drains lag dispatch by the window depth
            gi, p = window.pop(0)
            assert dev.drain(p) == oracle_dense[gi], f"batch {gi}"
    for gi, p in window:
        assert dev.drain(p) == oracle_dense[gi], f"batch {gi}"
    s = dev.spill.stats
    assert s["cycles"] >= 2, "tiny keep_frac must cycle within 60 batches"
    assert s["reloaded"] >= 1, "workload never exercised the reload path"
    assert s["prefetches"] >= 1 and s["prefetched"] >= 1, (
        "the prefetch/commit overlap path never served a reload"
    )
    assert s["lookup_batches"] >= 1
    rep = dev.spill.overlap_report()
    assert rep["spill_overlap"] is None or 0.0 <= rep["spill_overlap"] <= 1.0
    if s["lookup_batches"]:
        assert rep["spill_lookup_batch"] >= 1
    # full-state parity (HBM + LSM + staged + prefetched views merged)
    accounts, transfers, posted = dev.extract()
    assert accounts == oracle.accounts
    assert transfers == oracle.transfers
    assert posted == oracle.posted


def test_spill_deferred_io_stays_off_commit_path():
    """The replica-attached executor (DeferredSpillIO): LSM insertion
    queues at the commit and runs at io_pump/io_drain — the commit
    dispatch path itself never settles trees. Same-input determinism:
    two identical runs produce identical spilled sets and identical
    result codes with the deferred executor."""
    from tigerbeetle_tpu.types import Operation

    def run_once():
        _, forest = _forest()
        dev = DeviceLedger(process=TEST_PROCESS, mode="auto", forest=forest,
                           spill_io="deferred")
        gen = WorkloadGenerator(23, ledgers=(1,), invalid_rate=0.0,
                                conflict_rate=0.05, chain_rate=0.0,
                                two_phase_rate=0.1, balancing_rate=0.0,
                                limit_account_rate=0.0)
        ts = 1_000_000_000
        op, events = gen.gen_accounts_batch(40)
        ts += len(events)
        dev.execute_dense(op, ts, events)
        codes = []
        queued_after_cycle = 0
        for b in range(40):
            op, events = gen.gen_transfers_batch(96)
            ts += len(events)
            pre = dev.spill.stats["cycles"]
            codes.append(tuple(dev.execute_dense(op, ts, events)))
            if dev.spill.stats["cycles"] > pre:
                # the cycle queued its LSM insertion instead of running it
                queued_after_cycle = max(
                    queued_after_cycle, dev.spill.io_pending()
                )
            if b % 8 == 7:
                dev.spill.io_pump()  # the replica's tick-boundary pump
        assert dev.spill.stats["cycles"] >= 1
        assert queued_after_cycle >= 1, (
            "deferred executor ran LSM insertion inside the commit path"
        )
        dev.spill.io_drain()
        return codes, frozenset(dev.spill.spilled)

    run_a = run_once()
    run_b = run_once()
    assert run_a == run_b, "deferred spill IO broke same-input determinism"
