"""Async packet client ABI (native/tb_client.cc tb_client_async_*): the
reference's packet/completion model (src/clients/c/tb_client/packet.zig,
thread.zig) — N requests in flight from one process over a session pool,
same-op create packets coalesced into one message and their sparse results
demuxed per packet with rebased indices."""

import os
import subprocess
import sys

import pytest

from tigerbeetle_tpu.types import Account, Operation, Transfer

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def server(tmp_path_factory):
    from tests.test_process import _free_port, _kill_group, _spawn_server

    tmp = tmp_path_factory.mktemp("async_client")
    path = str(tmp / "data.tigerbeetle")
    port = _free_port()
    fmt = subprocess.run(
        [sys.executable, "-m", "tigerbeetle_tpu", "format",
         "--cluster", "0", "--replica", "0", "--replica-count", "1",
         "--grid-mb", "8", path],
        cwd=REPO, env=dict(os.environ, PYTHONPATH=REPO),
        capture_output=True, text=True, timeout=120,
    )
    assert fmt.returncode == 0, fmt.stderr
    proc = _spawn_server(path, port)
    yield {"proc": proc, "port": port}
    _kill_group(proc)


def test_concurrent_packets_end_to_end(server):
    """Many packets in flight at once; every reply lands on the right
    packet (ids/results verified through the blocking control session)."""
    from tigerbeetle_tpu import types
    from tigerbeetle_tpu.client_ffi import AsyncNativeClient, NativeClient
    from tigerbeetle_tpu.state_machine import decode_results

    addr = f"127.0.0.1:{server['port']}"
    ctl = NativeClient(addr)
    assert ctl.create_accounts(
        [Account(id=i, ledger=1, code=1) for i in (1, 2)]
    ) == []

    ac = AsyncNativeClient(addr, sessions=4)
    try:
        futs = []
        for g in range(16):
            transfers = [
                Transfer(id=1000 + g * 10 + i, debit_account_id=1,
                         credit_account_id=2, amount=1, ledger=1, code=1)
                for i in range(8)
            ]
            body = types.transfers_to_np(transfers).tobytes()
            futs.append(ac.submit(Operation.create_transfers, body))
        for f in futs:
            assert f.result(timeout=120) == b""  # all succeeded
    finally:
        ac.close()
    accounts = ctl.lookup_accounts([1, 2])
    assert accounts[0].debits_posted == 16 * 8
    assert accounts[1].credits_posted == 16 * 8

    # failures come back demuxed with correctly REBASED indices: submit
    # two single-event packets where only the second fails — its sparse
    # result must carry index 0 (not its index inside a coalesced message)
    ac2 = AsyncNativeClient(addr, sessions=1)
    try:
        ok_t = [Transfer(id=5000, debit_account_id=1, credit_account_id=2,
                         amount=1, ledger=1, code=1)]
        bad_t = [Transfer(id=5001, debit_account_id=1, credit_account_id=1,
                          amount=1, ledger=1, code=1)]  # same accounts
        f1 = ac2.submit(
            Operation.create_transfers, types.transfers_to_np(ok_t).tobytes()
        )
        f2 = ac2.submit(
            Operation.create_transfers, types.transfers_to_np(bad_t).tobytes()
        )
        assert f1.result(timeout=120) == b""
        res = decode_results(f2.result(timeout=120),
                             Operation.create_transfers)
        assert res == [(0, int(types.CreateTransferResult.accounts_must_be_different))]
    finally:
        ac2.close()
    ctl.close()


def test_async_lookup_packets(server):
    from tigerbeetle_tpu import types
    from tigerbeetle_tpu.client_ffi import AsyncNativeClient
    from tigerbeetle_tpu.state_machine import encode_ids

    import numpy as np

    addr = f"127.0.0.1:{server['port']}"
    ac = AsyncNativeClient(addr, sessions=2)
    try:
        f = ac.submit(Operation.lookup_accounts, encode_ids([1, 2, 404]))
        rows = np.frombuffer(f.result(timeout=120), dtype=types.ACCOUNT_DTYPE)
        assert len(rows) == 2  # 404 skipped
        assert sorted(int(r["id_lo"]) for r in rows) == [1, 2]
    finally:
        ac.close()


def test_async_driver_e2e_smoke():
    """run_e2e(driver="async"): the BASELINE protocol through the async
    ABI from one process, conservation verified over the wire."""
    from tigerbeetle_tpu.benchmark import run_e2e

    out = run_e2e(
        n_accounts=200, n_transfers=64 * 8, batch=64, clients=4,
        warmup_batches=1, jax_platform="cpu", backend="native",
        driver="async",
    )
    assert out["driver"] == "async_abi"
    assert out["durable_tps"] > 0


def test_async_driver_two_phase_smoke():
    from tigerbeetle_tpu.benchmark import run_e2e

    out = run_e2e(
        n_accounts=200, n_transfers=64 * 6, batch=64, clients=3,
        warmup_batches=1, jax_platform="cpu", backend="native+device",
        driver="async", workload="two_phase",
    )
    assert out["durable_tps"] > 0
    assert out["device_shadow"]["verified"] is True
