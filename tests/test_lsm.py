"""LSM storage engine: grid blocks, trees with flush/compaction/tombstones,
grooves with the prefetch contract, forest checkpoint/restore persistence
(reference: src/vsr/grid.zig, src/lsm/tree.zig, groove.zig, forest.zig)."""

import random

import pytest

from tigerbeetle_tpu.constants import TEST_CLUSTER
from tigerbeetle_tpu.io.storage import MemoryStorage, Zone, ZoneLayout
from tigerbeetle_tpu.lsm.grid import BLOCK_SIZE, Grid
from tigerbeetle_tpu.lsm.groove import Forest, Groove
from tigerbeetle_tpu.lsm.tree import Tree

LAYOUT = ZoneLayout(TEST_CLUSTER, grid_size=96 * 1024 * 1024)


def _grid(storage=None, cache_blocks=64):
    storage = storage or MemoryStorage(LAYOUT)
    return storage, Grid(storage, offset=0, block_count=640,
                         cache_blocks=cache_blocks)


def test_grid_block_roundtrip_and_checksum():
    storage, grid = _grid()
    a = grid.create_block(b"hello grid")
    b = grid.create_block(b"x" * 1000)
    assert grid.read_block(a) == b"hello grid"
    assert grid.read_block(b) == b"x" * 1000
    # corruption detected once the cache is bypassed
    grid.cache.clear()
    storage.fault(Zone.grid, (a - 1) * BLOCK_SIZE, 64)
    with pytest.raises(RuntimeError, match="checksum|corrupt"):
        grid.read_block(a)
    # release STAGES until the next checkpoint: a durable manifest may
    # still reference the block (crash-restore safety)
    grid.release(b)
    c = grid.acquire()
    assert c != b  # staged, not yet reusable
    grid.encode_free_set()  # checkpoint applies staged frees
    d = grid.acquire()
    assert d == b  # now the lowest free address again


def test_tree_put_get_flush_levels():
    _, grid = _grid()
    tree = Tree(grid, key_size=8, value_size=16, memtable_max=64)
    rng = random.Random(3)
    model = {}
    for i in range(1000):
        k = rng.randrange(500).to_bytes(8, "big")
        v = rng.getrandbits(120).to_bytes(16, "big")
        tree.put(k, v)
        model[k] = v
        if i % 100 == 50:
            tree.remove(k)
            model.pop(k)
    for k, v in model.items():
        assert tree.get(k) == v, k
    absent = (10_000).to_bytes(8, "big")
    assert tree.get(absent) is None
    # flushes happened (memtable_max=64 << 1000 puts) and levels exist
    assert any(tree.levels)


def test_put_array_settle_false_rejects_nonempty_memtable():
    """put_array(settle=False) documents 'touches no grid state, CANNOT
    raise' — the exactly-once building block of the spill fault-retry
    contract. A non-empty memtable would force a flush (which writes
    tables and can raise GridBlockCorrupt), so mixing put() with
    put_array(settle=False) must fail loudly instead of silently breaking
    the contract."""
    import numpy as np

    _, grid = _grid()
    tree = Tree(grid, key_size=8, value_size=8, memtable_max=64)
    keys = np.arange(4, dtype=np.uint64).byteswap().view(np.uint8)
    keys = keys.reshape(4, 8)
    vals = np.ones((4, 8), dtype=np.uint8)
    tree.put_array(keys, vals, settle=False)  # empty memtable: fine
    tree.put((99).to_bytes(8, "big"), b"\x01" * 8)  # memtable now dirty
    with pytest.raises(AssertionError, match="settle=False"):
        tree.put_array(keys, vals, settle=False)
    tree.put_array(keys, vals, settle=True)  # settle=True may flush


def test_tree_compaction_reclaims_blocks_and_drops_tombstones():
    _, grid = _grid()
    tree = Tree(grid, key_size=8, value_size=8, memtable_max=32)
    for i in range(400):
        tree.put(i.to_bytes(8, "big"), (i * 7).to_bytes(8, "big"))
    for i in range(0, 400, 2):
        tree.remove(i.to_bytes(8, "big"))
    tree.flush()
    # force full compaction to the bottom, one paced step at a time
    level = 0
    while level < len(tree.levels) - 1:
        while tree.levels[level]:
            tree._compact_one(level)
        level += 1
    for i in range(400):
        got = tree.get(i.to_bytes(8, "big"))
        if i % 2 == 0:
            assert got is None
        else:
            assert got == (i * 7).to_bytes(8, "big")
    # bottom level carries no tombstones: entry count == live keys
    assert sum(info.entry_count for info in tree.levels[-1]) == 200
    # superseded tables' blocks stage until a checkpoint applies them
    staged = len(grid._staged_free)
    assert staged > 0
    free_before = grid.free_set.count_free()
    grid.encode_free_set()  # checkpoint: staged frees become reusable
    assert grid.free_set.count_free() == free_before + staged


def test_groove_prefetch_contract():
    _, grid = _grid()
    g = Groove(grid, memtable_max=16)
    rows = {i: bytes([i % 251]) * 128 for i in range(1, 60)}
    for i, row in rows.items():
        g.insert(id_=i * 1000, timestamp=i, row=row)
    g.flush()
    g.prefetch([5000, 17000, 999_999])
    assert g.get(5000) == rows[5]
    assert g.get(17000) == rows[17]
    assert g.get(999_999) is None
    with pytest.raises(AssertionError):
        g.get(23000)  # not prefetched: the contract is explicit
    # upsert (same timestamp key) visible after re-prefetch
    g.upsert(id_=5000, timestamp=5, row=b"\xaa" * 128)
    g.prefetch_clear()
    g.prefetch([5000])
    assert g.get(5000) == b"\xaa" * 128


def test_forest_checkpoint_restore_over_storage():
    """Write through a forest, checkpoint, then reopen over the same
    storage bytes: all data readable, allocations consistent."""
    storage, grid = _grid()
    forest = Forest(grid)
    for i in range(1, 300):
        forest.accounts.insert(i, i, bytes([i % 250 + 1]) * 128)
        if i % 3 == 0:
            forest.transfers.insert(10_000 + i, 10_000 + i, b"\x07" * 128)
        if i % 5 == 0:
            forest.posted.put((10_000 + i).to_bytes(8, "big"), b"\x01")
    manifest = forest.checkpoint()

    # "restart": fresh objects over the same storage
    _, grid2 = _grid(storage)
    forest2 = Forest(grid2)
    forest2.restore(manifest)
    forest2.accounts.prefetch([5, 299, 100])
    assert forest2.accounts.get(5) == bytes([6]) * 128
    assert forest2.accounts.get(299) == bytes([299 % 250 + 1]) * 128
    forest2.transfers.prefetch([10_003])
    assert forest2.transfers.get(10_003) == b"\x07" * 128
    assert forest2.posted.get((10_005).to_bytes(8, "big")) == b"\x01"
    # free set restored: allocating doesn't clobber existing blocks
    before = grid2.free_set.count_free()
    addr = grid2.acquire()
    assert grid2.free_set.count_free() == before - 1
    grid2.write_block(addr, b"new data")
    forest2.accounts.prefetch_clear()
    forest2.accounts.prefetch([5])
    assert forest2.accounts.get(5) == bytes([6]) * 128  # intact


def test_manifest_log_incremental_and_compaction():
    """Checkpoints persist only NEW TableInfo churn as appended chain
    blocks; when churn dwarfs the live set the chain compacts to a snapshot
    and the old blocks release (reference: src/lsm/manifest_log.zig)."""
    storage, grid = _grid()
    forest = Forest(grid)
    model = {}
    meta = None
    for round_ in range(6):
        for i in range(400):
            k = (round_ * 400 + i) * 31 % 3000 + 1
            row = bytes([k % 251]) * 128
            forest.transfers.insert(id_=k, timestamp=round_ * 400 + i + 1,
                                    row=row)
            model[k] = (round_ * 400 + i + 1, row)
        meta = forest.checkpoint()
    assert meta["manifest_log"]["blocks"], "chain must exist"
    live = sum(len(t) for tree in forest._trees() for t in tree.levels)
    assert meta["manifest_log"]["events"] <= max(64, 8 * live), \
        "chain never compacted"
    # restore into a fresh forest over the same storage
    forest2 = Forest(Grid(storage, offset=0, block_count=640,
                          cache_blocks=64))
    forest2.restore(meta)
    for k, (ts, row) in list(model.items())[::37]:
        g = forest2.transfers
        ts_key = g.ids.get(g._id_key(k))
        assert ts_key is not None, k
        assert g.objects.get(ts_key) == row, k
    # the levels metadata must round-trip exactly
    for t1, t2 in zip(forest._trees(), forest2._trees()):
        assert [
            [i.to_json() for i in lv] for lv in t1.levels if lv
        ] == [
            [i.to_json() for i in lv] for lv in t2.levels if lv
        ], t1.tree_id


def test_tree_get_many_matches_get():
    """The vectorized multi-point-read must equal a per-key get() cascade
    across every residency class: memtable, level 0, deeper levels,
    tombstones, misses — including keys updated at several depths
    (newest-wins resolution order)."""
    _, grid = _grid()
    tree = Tree(grid, key_size=8, value_size=16, memtable_max=64)
    rng = random.Random(7)
    model = {}
    for i in range(2500):
        k = rng.randrange(900).to_bytes(8, "big")
        v = rng.getrandbits(120).to_bytes(16, "big")
        tree.put(k, v)
        model[k] = v
        if i % 90 == 40:
            tree.remove(k)
            model.pop(k)
    # probe set: every written key + misses, in shuffled order with dups
    probes = [k.to_bytes(8, "big") for k in range(950)]
    rng.shuffle(probes)
    probes += probes[:37]  # duplicates resolve identically
    got = tree.get_many(probes)
    assert got == [tree.get(k) for k in probes]
    assert got == [model.get(k) for k in probes]
    # legacy filter versions take the scalar fallback path
    from tigerbeetle_tpu.lsm.tree import filter_may_contain_many
    import numpy as np

    keys_u8 = np.frombuffer(b"".join(probes[:64]), dtype=np.uint8)
    keys_u8 = keys_u8.reshape(64, 8)
    for info in tree.levels[0] + [t for lvl in tree.levels[1:] for t in lvl]:
        if not info.filter_address:
            continue
        filt = grid.read_block(info.filter_address)
        many = filter_may_contain_many(filt, keys_u8,
                                       version=info.filter_version)
        from tigerbeetle_tpu.lsm.tree import filter_may_contain

        assert list(many) == [
            filter_may_contain(filt, bytes(k), version=info.filter_version)
            for k in keys_u8
        ]
        break


def test_groove_get_many_rows():
    """Batched id -> row resolution through IdTree + ObjectTree equals the
    per-id prefetch cascade."""
    _, grid = _grid()
    g = Groove(grid, memtable_max=32)
    rows = {}
    for i in range(1, 300):
        row = bytes([i % 251]) * 128
        g.insert(i, 1000 + i, row)
        rows[i] = row
    ids = list(range(1, 320))  # includes misses
    got_rows, got_ts = g.get_many_rows(ids)
    for id_, row, tsk in zip(ids, got_rows, got_ts):
        if id_ in rows:
            assert row == rows[id_], id_
            assert tsk == (1000 + id_).to_bytes(8, "big")
        else:
            assert row is None and tsk is None
