"""The VOPR hub (scripts/vopr_hub.py; reference: src/vopr_hub/ — dedupe
crashing seeds by signature, replay to confirm, file one issue each)."""

import json
import subprocess
import sys
from pathlib import Path

from scripts.vopr_hub import ingest, sig_id, signature

REPO = str(Path(__file__).resolve().parent.parent)


def test_signature_normalizes_varying_numbers():
    a = signature("AssertionError: history fork at op 17 (replica 2)")
    b = signature("AssertionError: history fork at op 9301 (replica 0)")
    assert a == b
    c = signature("AssertionError: checksum 0xdeadbeef != 0xcafe")
    d = signature("AssertionError: checksum 0x1234 != 0x99")
    assert c == d
    assert a != c


def test_ingest_groups_and_files_reports(tmp_path):
    fleet = tmp_path / "fleet.jsonl"
    recs = [
        {"seed": 1, "ticks": 100, "topology": "r3+s0 c2x4 oracle", "ok": True,
         "stats": {}},
        {"seed": 2, "ticks": 100, "topology": "r2+s1 c1x4 oracle", "ok": False,
         "error": "AssertionError: history fork at op 12 (replica 1)"},
        {"seed": 3, "ticks": 100, "topology": "r4+s0 c3x2 oracle", "ok": False,
         "error": "AssertionError: history fork at op 99 (replica 3)"},
        {"seed": 4, "ticks": 100, "topology": "r1+s0 c2x8 oracle", "ok": False,
         "error": "ValueError: Sample larger than population or is negative"},
    ]
    fleet.write_text("\n".join(json.dumps(r) for r in recs) + "\n")
    groups = ingest(str(fleet))
    assert len(groups) == 2  # two unique signatures, seeds 2+3 deduped
    fork = [g for g in groups.values() if "fork" in g["sig"]][0]
    assert [r["seed"] for r in fork["records"]] == [2, 3]

    # the CLI files one report per signature and exits 2 (failures exist)
    out = tmp_path / "issues"
    p = subprocess.run(
        [sys.executable, "scripts/vopr_hub.py", str(fleet),
         "--out", str(out)],
        cwd=REPO, capture_output=True, text=True, timeout=120,
    )
    assert p.returncode == 2, p.stderr
    reports = list(out.glob("*.md"))
    assert len(reports) == 2
    body = "\n".join(r.read_text() for r in reports)
    assert "--start 2" in body and "--start 4" in body
    assert sig_id(fork["sig"]) in body


def test_replay_passes_recorded_verify_fraction(monkeypatch):
    """Hub replays must reproduce the fleet's slice draws exactly: the
    per-seed record carries verify_fraction AND cdc_fraction (like
    device_fraction and fixed) and replay() passes them through; a legacy
    record without the fields falls back to the fleet defaults."""
    import scripts.vopr as vopr_mod
    from scripts.vopr_hub import replay

    seen = {}

    def fake_run_seed(seed, ticks, device_fraction=0.0, fixed=False,
                      verify_fraction=None, cdc_fraction=None,
                      ingress_fraction=None, federation_fraction=None,
                      trace_path=None):
        seen.update(seed=seed, verify_fraction=verify_fraction,
                    cdc_fraction=cdc_fraction,
                    ingress_fraction=ingress_fraction,
                    federation_fraction=federation_fraction,
                    trace_path=trace_path)
        return None, "r3", None

    monkeypatch.setattr(vopr_mod, "run_seed", fake_run_seed)
    rec = {"seed": 7, "ticks": 50, "topology": "r3 c2",
           "verify_fraction": 0.6, "cdc_fraction": 0.5,
           "ingress_fraction": 0.4, "federation_fraction": 0.3,
           "trace": "/tmp/t.7.json",
           "ok": False, "error": "X"}
    replay(rec)
    assert seen["verify_fraction"] == 0.6
    assert seen["cdc_fraction"] == 0.5
    assert seen["ingress_fraction"] == 0.4
    assert seen["federation_fraction"] == 0.3
    # a fleet run with --trace recorded the per-seed stitched trace
    # path: the replay dumps at a SIBLING path so a diverging replay
    # stays diffable against the fleet's original artifact
    assert seen["trace_path"] == "/tmp/t.7.json.replay.json"
    # legacy record (pre-field): the defaults apply
    replay({"seed": 8, "ticks": 50, "topology": "r3 c2",
            "ok": False, "error": "X"})
    assert seen["verify_fraction"] == vopr_mod.VERIFY_FRACTION_DEFAULT
    assert seen["cdc_fraction"] == vopr_mod.CDC_FRACTION_DEFAULT
    assert seen["ingress_fraction"] == vopr_mod.INGRESS_FRACTION_DEFAULT
    assert (seen["federation_fraction"]
            == vopr_mod.FEDERATION_FRACTION_DEFAULT)
    assert seen["trace_path"] is None


def test_federation_slice_routes_to_federation_sim(monkeypatch):
    """The federation draw is EXCLUSIVE: a drawn seed runs the two-region
    composite (federation/sim.py) instead of a single Simulator, tagged
    FED in the topology line; fraction 0 disables the slice entirely. The
    draw uses a distinct multiplier, so it must be decorrelated from the
    VERIFY/CDC/INGRESS draws (not a subset/superset of any of them)."""
    import scripts.vopr as vopr_mod
    from tigerbeetle_tpu.federation import sim as fed_sim

    called = {}

    def fake_fed_sim(seed, ticks=0):
        called.update(seed=seed, ticks=ticks)
        return {"seed": seed, "issued": 0}

    monkeypatch.setattr(fed_sim, "run_federation_sim", fake_fed_sim)
    drawn = [s for s in range(1, 400)
             if (s * 3266489917) % 100 < 10]
    assert 30 <= len(drawn) <= 50  # ~10% of seeds
    seed = drawn[0]
    stats, desc, err = vopr_mod.run_seed(
        seed, ticks=50, device_fraction=0.0, fixed=False)
    assert err is None and "FED" in desc
    assert called["seed"] == seed
    assert called["ticks"] >= 1200  # floor: the drain needs room
    # fraction 0 turns the slice off — the seed runs the normal draw
    called.clear()
    _, desc0, _ = vopr_mod.run_seed(
        seed, ticks=5, device_fraction=0.0, fixed=False,
        federation_fraction=0.0)
    assert "FED" not in desc0 and not called
    # decorrelation: the FED set is not nested in any sibling slice
    for mult, frac in ((2654435761, 0.25), (2246822519, 0.2),
                       (2166136261, 0.15)):
        other = {s for s in range(1, 400) if (s * mult) % 100 < frac * 100}
        assert not set(drawn) <= other
        assert not other <= set(drawn)


def test_hub_clean_fleet_exits_zero(tmp_path):
    fleet = tmp_path / "fleet.jsonl"
    fleet.write_text(json.dumps(
        {"seed": 1, "ticks": 100, "topology": "r3", "ok": True, "stats": {}}
    ) + "\n")
    p = subprocess.run(
        [sys.executable, "scripts/vopr_hub.py", str(fleet)],
        cwd=REPO, capture_output=True, text=True, timeout=120,
    )
    assert p.returncode == 0, p.stderr
    assert "no failures" in p.stdout
