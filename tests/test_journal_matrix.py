"""Journal recovery decision matrix: misdirected-write and wrap-stale
rows (reference: src/vsr/journal.zig:374-535; VERDICT r3 item 10).
"""

from tigerbeetle_tpu import types
from tigerbeetle_tpu.constants import TEST_CLUSTER
from tigerbeetle_tpu.io.storage import MemoryStorage, Zone, ZoneLayout
from tigerbeetle_tpu.vsr.header import Command, Header
from tigerbeetle_tpu.vsr.journal import Journal

LAYOUT = ZoneLayout(TEST_CLUSTER, grid_size=8 * 1024 * 1024)


def _prepare(op: int, parent: int = 0) -> tuple[Header, bytes]:
    body = types.accounts_to_np(
        [types.Account(id=1000 + op, ledger=1, code=1)]
    ).tobytes()
    h = Header(
        command=int(Command.prepare),
        operation=int(types.Operation.create_accounts),
        op=op, parent=parent, timestamp=1 << 30 | op,
    )
    h.set_checksum_body(body)
    h.set_checksum()
    return h, body


def _journal():
    storage = MemoryStorage(LAYOUT)
    return storage, Journal(storage, TEST_CLUSTER)


def test_misdirected_write_classified_and_not_trusted():
    storage, j = _journal()
    for op in range(1, 6):
        h, body = _prepare(op)
        j.write_prepare(h, body)
    # misdirect: op 3's (checksum-valid) prepare lands in op 4's slot
    msg_max = TEST_CLUSTER.message_size_max
    raw3 = storage.read(Zone.wal_prepares, j.slot_for_op(3) * msg_max, msg_max)
    storage.write(Zone.wal_prepares, j.slot_for_op(4) * msg_max, raw3)

    j2 = Journal(storage, TEST_CLUSTER)
    out = j2.recover()
    assert j2.recover_stats["misdirected"] == 1
    # the misdirected prepare is NOT evidence for slot 4; the redundant
    # ring's header for op 4 marks the slot faulty/repairable
    assert 4 not in out
    assert j2.faulty[j2.slot_for_op(4)] == 4
    assert j2.get_header(4) is not None  # mirror keeps the true evidence
    assert 3 in out  # op 3's own slot is untouched


def test_wrap_stale_prepare_yields_newer_ops_evidence():
    """A surviving previous-ring-pass prepare underneath a newer op's
    redundant header: the header (written only AFTER its prepare once
    landed) wins; the slot is faulty for the NEWER op — trusting the stale
    prepare would advertise a superseded op in DVCs."""
    storage, j = _journal()
    slots = TEST_CLUSTER.journal_slot_count
    h_old, body_old = _prepare(7)
    j.write_prepare(h_old, body_old)
    old_raw = storage.read(
        Zone.wal_prepares, j.slot_for_op(7) * TEST_CLUSTER.message_size_max,
        TEST_CLUSTER.message_size_max,
    )
    h_new, body_new = _prepare(7 + slots)  # same slot, next ring pass
    j.write_prepare(h_new, body_new)
    # the new prepare's write is rolled back (crash during overwrite);
    # the redundant header for the new op survives
    storage.write(
        Zone.wal_prepares, j.slot_for_op(7) * TEST_CLUSTER.message_size_max,
        old_raw,
    )

    j2 = Journal(storage, TEST_CLUSTER)
    out = j2.recover()
    assert j2.recover_stats["wrap_stale"] == 1
    assert 7 not in out, "superseded prepare must not be replayable"
    assert (7 + slots) not in out
    assert j2.faulty[j2.slot_for_op(7)] == 7 + slots
    assert j2.get_header(7 + slots) is not None


def test_torn_header_row_prepare_wins():
    storage, j = _journal()
    h, body = _prepare(2)
    j.write_prepare(h, body)
    # tear the redundant header's bytes (torn header-sector write)
    storage.fault(Zone.wal_headers, j.slot_for_op(2) * 128, 64)
    j2 = Journal(storage, TEST_CLUSTER)
    out = j2.recover()
    assert 2 in out and out[2].checksum == h.checksum
    assert j2.recover_stats["torn_header"] >= 1
