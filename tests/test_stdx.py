"""Foundation data structures + EWAH + free set + config fingerprint
(reference: src/ring_buffer.zig, src/fifo.zig, src/iops.zig, src/ewah.zig,
src/vsr/superblock_free_set.zig, src/config.zig fingerprint)."""

import random

import pytest

from tigerbeetle_tpu.constants import ConfigCluster, TEST_CLUSTER
from tigerbeetle_tpu.stdx import FIFO, IOPS, RingBuffer, ewah_decode, ewah_encode
from tigerbeetle_tpu.vsr.free_set import FreeSet


def test_ring_buffer():
    rb = RingBuffer(3)
    rb.push(1)
    rb.push(2)
    assert list(rb) == [1, 2] and len(rb) == 2
    assert rb.pop() == 1
    rb.push(3)
    rb.push(4)
    assert rb.full
    with pytest.raises(AssertionError):
        rb.push(5)
    assert [rb.pop() for _ in range(3)] == [2, 3, 4]
    with pytest.raises(AssertionError):
        rb.pop()


def test_fifo_intrusive():
    class Item:
        next = None

        def __init__(self, v):
            self.v = v

    f = FIFO()
    items = [Item(i) for i in range(5)]
    for it in items:
        f.push(it)
    assert len(f) == 5
    assert [f.pop().v for _ in range(5)] == [0, 1, 2, 3, 4]
    assert f.pop() is None


def test_iops_pool():
    pool = IOPS(4)
    slots = [pool.acquire() for _ in range(4)]
    assert sorted(slots) == [0, 1, 2, 3]
    assert pool.acquire() is None  # exhausted: backpressure, not allocation
    assert pool.executing == 4
    pool.release(2)
    assert pool.acquire() == 2
    with pytest.raises(AssertionError):
        pool.release(3) or pool.release(3)  # double release


def test_ewah_roundtrip():
    rng = random.Random(7)
    cases = [
        [0] * 100,
        [(1 << 64) - 1] * 100,
        [rng.getrandbits(64) for _ in range(50)],
        [0] * 10 + [123, 456] + [(1 << 64) - 1] * 20 + [789] + [0] * 5,
        [],
    ]
    for words in cases:
        enc = ewah_encode(words)
        assert ewah_decode(enc, len(words)) == words
    # compression: a sparse bitset shrinks dramatically
    sparse = [0] * 1000
    sparse[500] = 1 << 17
    assert len(ewah_encode(sparse)) < 100  # vs 8000 raw bytes


def test_free_set_disjoint_reservations():
    fs = FreeSet(256)
    r1 = fs.reserve(10)
    r2 = fs.reserve(10)  # must NOT overlap r1's window
    assert r1.block_base + r1.block_count <= r2.block_base
    a1 = [fs.acquire(r1) for _ in range(10)]
    a2 = [fs.acquire(r2) for _ in range(10)]
    assert set(a1).isdisjoint(a2)
    fs.forfeit(r1)
    fs.forfeit(r2)
    # all forfeited: the scan window resets
    assert fs.reserve(5).block_base >= 0


def test_ewah_truncation_detected():
    words = [7, 8, 9]
    enc = ewah_encode(words)
    with pytest.raises(ValueError):
        ewah_decode(enc[:-3], 3)
    with pytest.raises(ValueError):
        ewah_decode(enc, 5)  # fewer words than promised


def test_free_set_reservations_and_trailer():
    fs = FreeSet(256)
    assert fs.count_free() == 256
    r = fs.reserve(10)
    addrs = [fs.acquire(r) for _ in range(10)]
    assert addrs == list(range(1, 11))
    assert fs.count_free() == 246
    fs.forfeit(r)
    with pytest.raises(AssertionError):
        fs.acquire(r)  # stale reservation session
    fs.release(5)
    with pytest.raises(AssertionError):
        fs.release(5)  # double free
    # trailer roundtrip (EWAH over the words)
    enc = fs.encode()
    fs2 = FreeSet.decode(enc, 256)
    assert fs2.words == fs.words
    assert not fs2.is_free(1) and fs2.is_free(5)


def test_config_fingerprint_guard():
    from tigerbeetle_tpu.io.storage import MemoryStorage, ZoneLayout
    from tigerbeetle_tpu.vsr.durable import (
        check_config_fingerprint,
        format_data_file,
    )
    from tigerbeetle_tpu.vsr.superblock import SuperBlock

    a = TEST_CLUSTER
    b = ConfigCluster(journal_slot_count=128, lsm_batch_multiple=4)
    assert a.fingerprint() != b.fingerprint()
    assert a.fingerprint() == ConfigCluster(
        journal_slot_count=64, lsm_batch_multiple=4
    ).fingerprint()

    storage = MemoryStorage(ZoneLayout(a, grid_size=1 << 20))
    format_data_file(storage, a)
    state = SuperBlock(storage).open()
    check_config_fingerprint(state, a)  # matching: fine
    with pytest.raises(RuntimeError, match="different cluster config"):
        check_config_fingerprint(state, b)
