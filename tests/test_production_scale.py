"""Production-scale parity (VERDICT round-1 item 7): full 8190-event
batches with tables filled to the 1/2 load-factor limit — the regime where
the digit-accumulator bound, claim contention under 16384 concurrent insert
lanes, and long probe chains actually live — plus the device-side occupancy
guard (host bypassed)."""

import numpy as np
import pytest

from tigerbeetle_tpu.constants import BATCH_PAD, ConfigProcess
from tigerbeetle_tpu.models.ledger import (
    FAULT_CAPACITY,
    DeviceLedger,
    accounts_to_batch,
)
from tigerbeetle_tpu.models.oracle import OracleStateMachine
from tigerbeetle_tpu.testing.workload import WorkloadGenerator
from tigerbeetle_tpu.types import ACCOUNT_DTYPE, TRANSFER_DTYPE, Operation

BATCH = 8190


def _accounts_np(start, n, ledger=1):
    arr = np.zeros(n, dtype=ACCOUNT_DTYPE)
    arr["id_lo"] = np.arange(start, start + n, dtype=np.uint64)
    arr["ledger"] = ledger
    arr["code"] = 1
    return arr


def _transfers_np(rng, start_id, n, n_accounts, ledger=1):
    arr = np.zeros(n, dtype=TRANSFER_DTYPE)
    arr["id_lo"] = np.arange(start_id, start_id + n, dtype=np.uint64)
    dr = rng.integers(1, n_accounts + 1, size=n, dtype=np.uint64)
    off = rng.integers(1, n_accounts, size=n, dtype=np.uint64)
    arr["debit_account_id_lo"] = dr
    arr["credit_account_id_lo"] = (dr - 1 + off) % n_accounts + 1
    # large amounts: every 16-bit digit lane of the accumulator is exercised
    arr["amount_lo"] = rng.integers(1, 1 << 48, size=n, dtype=np.uint64)
    arr["ledger"] = ledger
    arr["code"] = 1
    return arr


@pytest.mark.slow
def test_full_batch_parity_at_load_limit():
    """8190-lane fast-tier batches filling the tables to their load-factor
    limit, bit-exact against the oracle."""
    process = ConfigProcess(account_slots_log2=14, transfer_slots_log2=15)
    dev = DeviceLedger(process=process, mode="auto")
    dev.pad_to = BATCH_PAD
    oracle = OracleStateMachine()
    rng = np.random.default_rng(3)
    ts = 1 << 30

    # accounts: one full batch -> 8190 of 8192 permitted slots (limit edge)
    accounts = _accounts_np(1, BATCH)
    ts += BATCH
    assert oracle.execute_dense(Operation.create_accounts, ts, accounts) == \
        dev.execute_dense(Operation.create_accounts, ts, accounts)

    # transfers: two full batches -> 16380 of 16384 permitted slots
    for b in range(2):
        xfers = _transfers_np(rng, 1 + b * BATCH, BATCH, BATCH)
        ts += BATCH
        dense_o = oracle.execute_dense(Operation.create_transfers, ts, xfers)
        dense_d = dev.execute_dense(Operation.create_transfers, ts, xfers)
        assert dense_d == dense_o, f"batch {b}"

    accounts_d, transfers_d, posted_d = dev.extract()
    assert accounts_d == oracle.accounts
    assert transfers_d == oracle.transfers
    assert posted_d == oracle.posted

    # the next full batch would exceed the limit: host guard fires first
    with pytest.raises(RuntimeError, match="load-factor"):
        dev.execute_dense(
            Operation.create_transfers, ts + BATCH,
            _transfers_np(rng, 1 + 2 * BATCH, BATCH, BATCH),
        )


@pytest.mark.slow
def test_full_batch_serial_tier_parity():
    """A full 8190-event batch through the exact serial tier (hazards:
    chains, two-phase, balancing, duplicates) — parity at the batch size
    where the 8192-step scan really runs."""
    process = ConfigProcess(account_slots_log2=12, transfer_slots_log2=14)
    dev = DeviceLedger(process=process, mode="auto")
    dev.pad_to = BATCH_PAD
    oracle = OracleStateMachine()
    gen = WorkloadGenerator(55)
    ts = 1 << 30

    op, accounts = gen.gen_accounts_batch(1500)
    ts += len(accounts)
    assert oracle.execute_dense(op, ts, accounts) == \
        dev.execute_dense(op, ts, accounts)

    op, xfers = gen.gen_transfers_batch(BATCH)
    ts += len(xfers)
    dense_o = oracle.execute_dense(op, ts, xfers)
    dense_d = dev.execute_dense(op, ts, xfers)
    assert dense_d == dense_o
    accounts_d, transfers_d, posted_d = dev.extract()
    assert accounts_d == oracle.accounts
    assert transfers_d == oracle.transfers
    assert posted_d == oracle.posted


def test_device_side_capacity_guard_bypassing_host():
    """Drive the kernels DIRECTLY (as a desynced host would): the device
    must refuse to fill past the load-factor limit with a sticky
    FAULT_CAPACITY no-op, for both tiers."""
    import jax.numpy as jnp

    from tigerbeetle_tpu.models.ledger import LedgerKernels, init_state

    process = ConfigProcess(account_slots_log2=6, transfer_slots_log2=8)
    kernels = LedgerKernels(process)
    state = init_state(process)
    ts = 1000

    # fast tier: 40 accounts > 32-slot limit -> whole batch no-op + fault
    batch = accounts_to_batch(_accounts_np(1, 40), 64)
    state2, r = kernels.commit_accounts(
        state, batch, jnp.int32(40), jnp.uint64(ts + 40), mode="fast"
    )
    assert int(np.asarray(state2["fault"])) & FAULT_CAPACITY
    assert int(np.asarray(state2["acct_count"])) == 0  # nothing applied
    occupied = np.asarray(state2["acct_rows"])[:, :4].any(axis=1).sum()
    assert occupied == 0

    # serial tier: same guard at entry
    state = init_state(process)
    state2, r = kernels.commit_accounts(
        state, batch, jnp.int32(40), jnp.uint64(ts + 40), mode="serial"
    )
    assert int(np.asarray(state2["fault"])) & FAULT_CAPACITY
    assert int(np.asarray(state2["acct_count"])) == 0

    # under the limit: both tiers proceed and track used slots
    state = init_state(process)
    batch = accounts_to_batch(_accounts_np(1, 20), 32)
    state2, r = kernels.commit_accounts(
        state, batch, jnp.int32(20), jnp.uint64(ts + 20), mode="fast"
    )
    assert int(np.asarray(state2["fault"])) == 0
    assert int(np.asarray(state2["acct_used_slots"])) == 20
