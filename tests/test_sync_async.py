"""Async sync-payload serving (VERDICT r3 item 8): building the
O(checkpoint) image must not stall the event loop — requests arriving
mid-build get no reply (the peer's retry is the backpressure) and the
served bytes equal the synchronous build."""

import time

from tigerbeetle_tpu import types
from tigerbeetle_tpu.testing.cluster import Cluster
from tigerbeetle_tpu.testing.workload import WorkloadGenerator


def _loaded_replica():
    cluster = Cluster(replica_count=3)
    client = cluster.add_client()
    gen = WorkloadGenerator(41)
    for _ in range(3):
        op, events = gen.gen_accounts_batch(16)
        cluster.execute(client, op, types.accounts_to_np(events).tobytes())
    r = cluster.replicas[0]
    r.checkpoint()
    return cluster, r


def test_async_build_serves_after_future_resolves():
    _cluster, r = _loaded_replica()
    # deterministic harness pinned it off; turn the production mode on
    r.sync_payload_async = True
    r._sync_payload_cache = None

    got = r._sync_checkpoint_payload()
    assert got is None, "first call must only START the build"
    assert r._sync_payload_fut is not None

    deadline = time.monotonic() + 30
    while not r._sync_payload_fut.done():
        assert time.monotonic() < deadline
        time.sleep(0.01)
    served = r._sync_checkpoint_payload()
    assert served is not None

    # equals the synchronous build byte-for-byte
    r.sync_payload_async = False
    r._sync_payload_cache = None
    sync_built = r._sync_checkpoint_payload()
    assert served == sync_built


def test_mid_build_requests_are_dropped_not_blocking():
    """_on_request_sync_checkpoint with the build in flight sends nothing
    and returns immediately (no O(checkpoint) stall in _on_message)."""
    from tigerbeetle_tpu.vsr.header import Command, Header

    _cluster, r = _loaded_replica()
    r.sync_payload_async = True
    r._sync_payload_cache = None

    sent = []
    orig_send = r.network.send
    r.network.send = lambda src, dst, data: sent.append(dst)
    try:
        rq = Header(command=int(Command.request_sync_manifest), op=0)
        rq.replica = 1
        t0 = time.monotonic()
        r._on_request_sync_checkpoint(rq)
        assert time.monotonic() - t0 < 0.05, "serving blocked on the build"
        assert sent == []  # nothing served mid-build
    finally:
        r.network.send = orig_send

    deadline = time.monotonic() + 30
    while not r._sync_payload_fut.done():
        assert time.monotonic() < deadline
        time.sleep(0.01)
    # retry after the build lands: a chunk goes out
    rq2 = Header(command=int(Command.request_sync_manifest), op=0)
    rq2.replica = 1
    r._on_request_sync_checkpoint(rq2)
