"""Wire-level StateMachine: all four ops through one entry point, reply
bytes identical between the oracle backend and the device backend
(reference: src/tigerbeetle.zig:231-249 result structs,
src/state_machine.zig:701-736 lookups)."""

import numpy as np

from tigerbeetle_tpu import types
from tigerbeetle_tpu.constants import TEST_PROCESS
from tigerbeetle_tpu.models.ledger import DeviceLedger
from tigerbeetle_tpu.models.oracle import OracleStateMachine
from tigerbeetle_tpu.state_machine import (
    StateMachine,
    decode_ids,
    decode_results,
    encode_ids,
    encode_results,
)
from tigerbeetle_tpu.testing.workload import WorkloadGenerator
from tigerbeetle_tpu.types import Operation


def test_result_encoding_roundtrip():
    sparse = [(0, 21), (5, 1), (8190, 46)]
    body = encode_results(sparse, Operation.create_transfers)
    assert len(body) == 8 * len(sparse)
    assert decode_results(body, Operation.create_transfers) == sparse
    # Little-endian u32 pairs on the wire.
    arr = np.frombuffer(body, dtype="<u4")
    assert list(arr[:2]) == [0, 21]


def test_id_encoding_roundtrip():
    ids = [1, (1 << 128) - 2, 0xDEADBEEF << 64]
    body = encode_ids(ids)
    assert len(body) == 16 * len(ids)
    assert decode_ids(body) == ids


def test_wire_parity_all_ops():
    oracle = StateMachine(OracleStateMachine())
    dev = StateMachine(DeviceLedger(process=TEST_PROCESS, mode="auto"))
    gen = WorkloadGenerator(11)
    ts = 1_000_000_000

    for b in range(8):
        if b % 3 == 0:
            op, events = gen.gen_accounts_batch(24)
            body = types.accounts_to_np(events).tobytes()
        else:
            op, events = gen.gen_transfers_batch(24)
            body = types.transfers_to_np(events).tobytes()
        assert oracle.input_valid(op, body) and dev.input_valid(op, body)
        assert oracle.input_count(op, body) == len(events)
        ts += len(events)
        reply_o = oracle.commit(op, ts, body)
        reply_d = dev.commit(op, ts, body)
        assert reply_o == reply_d, f"batch {b} ({op.name})"

    for kind in ("accounts", "transfers"):
        op, ids = gen.gen_lookup_batch(30, kind)
        body = encode_ids(ids)
        assert oracle.input_valid(op, body)
        reply_o = oracle.commit(op, ts, body)
        reply_d = dev.commit(op, ts, body)
        assert reply_o == reply_d, kind
        assert len(reply_o) % 128 == 0


def test_sparse_encoding_matches_oracle_sparse():
    """The dense->sparse conversion must equal the oracle's native sparse
    output, including FIFO-ordered chain rollback entries."""
    from tigerbeetle_tpu.types import Account, Transfer

    o1 = OracleStateMachine()
    o2 = OracleStateMachine()
    sm = StateMachine(o2)
    ts = 10_000
    accounts = [Account(id=i, ledger=1, code=1) for i in (1, 2, 3)]
    ts += 3
    o1.execute(Operation.create_accounts, ts, accounts)
    sm.commit(Operation.create_accounts, ts, types.accounts_to_np(accounts).tobytes())

    # linked chain failing at the end -> rollback entries precede the failure.
    transfers = [
        Transfer(id=10, debit_account_id=1, credit_account_id=2, amount=5,
                 ledger=1, code=1, flags=1),
        Transfer(id=11, debit_account_id=2, credit_account_id=3, amount=7,
                 ledger=1, code=1, flags=1),
        Transfer(id=12, debit_account_id=1, credit_account_id=3, amount=0,
                 ledger=1, code=1),
    ]
    ts += 3
    sparse_native = o1.execute(Operation.create_transfers, ts, transfers)
    reply = sm.commit(
        Operation.create_transfers, ts, types.transfers_to_np(transfers).tobytes()
    )
    assert decode_results(reply, Operation.create_transfers) == sparse_native
    assert sparse_native == [(0, 1), (1, 1), (2, 18)]


def test_input_validation():
    sm = StateMachine(OracleStateMachine())
    assert not sm.input_valid(Operation.create_accounts, b"")
    assert not sm.input_valid(Operation.create_accounts, b"x" * 127)
    assert not sm.input_valid(Operation.lookup_accounts, b"x" * 15)
    assert not sm.input_valid(Operation.create_accounts, b"\0" * 128 * 8192)
    assert sm.input_valid(Operation.create_accounts, b"\0" * 128 * 8191)
    assert not sm.input_valid(Operation.register, b"")
