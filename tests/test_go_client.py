"""Go client integration: builds the cgo wrapper + two-phase sample and
runs it against a live server (reference: src/clients/go sample tests).
The CI image ships no Go toolchain, so this skips unless `go` is on PATH
— the Python/C client e2e (tests/test_process.py) covers the same wire
surface either way."""

import os
import shutil
import subprocess
import sys

import pytest

from tests.test_process import REPO, _free_port, _spawn_server

pytestmark = pytest.mark.skipif(
    shutil.which("go") is None, reason="no Go toolchain in this image"
)


def test_go_sample_two_phase(tmp_path):
    path = str(tmp_path / "data.tigerbeetle")
    port = _free_port()
    env = dict(os.environ, PYTHONPATH=REPO)
    fmt = subprocess.run(
        [sys.executable, "-m", "tigerbeetle_tpu", "format",
         "--cluster", "0", "--replica", "0", "--replica-count", "1",
         "--grid-mb", "8", path],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=120,
    )
    assert fmt.returncode == 0, fmt.stderr
    proc = _spawn_server(path, port)
    try:
        native = os.path.join(REPO, "native")
        goenv = dict(
            os.environ,
            CGO_ENABLED="1",
            CGO_CFLAGS=f"-I{native}",
            CGO_LDFLAGS=f"-L{native} -ltb_native -Wl,-rpath,{native}",
        )
        build = subprocess.run(
            ["go", "build", "-o", str(tmp_path / "sample"), "./sample"],
            cwd=os.path.join(REPO, "clients", "go"),
            env=goenv, capture_output=True, text=True, timeout=300,
        )
        assert build.returncode == 0, build.stderr
        run = subprocess.run(
            [str(tmp_path / "sample"), f"127.0.0.1:{port}"],
            capture_output=True, text=True, timeout=120,
        )
        assert run.returncode == 0, run.stdout + run.stderr
        assert "two-phase balances verified" in run.stdout
    finally:
        proc.kill()
