"""Durability: checksum, header, WAL journal, superblock quorum, and
crash/recovery of the device ledger (VERDICT round-1 item 5).

Model: reference two-level durability — WAL-before-commit + checkpointed
state + replay (reference: src/vsr/journal.zig, src/vsr/superblock.zig,
src/vsr/replica.zig:3489-3561)."""

import os
import subprocess
import sys

import pytest

from tigerbeetle_tpu import native, types
from tigerbeetle_tpu.constants import TEST_CLUSTER, TEST_PROCESS
from tigerbeetle_tpu.io.storage import (
    MemoryStorage,
    SECTOR_SIZE,
    Zone,
    ZoneLayout,
)
from tigerbeetle_tpu.models.oracle import OracleStateMachine
from tigerbeetle_tpu.state_machine import StateMachine
from tigerbeetle_tpu.testing.workload import WorkloadGenerator
from tigerbeetle_tpu.types import Operation
from tigerbeetle_tpu.vsr.durable import DurableLedger, format_data_file
from tigerbeetle_tpu.vsr.header import Command, Header
from tigerbeetle_tpu.vsr.journal import Journal
from tigerbeetle_tpu.vsr.superblock import SuperBlock, VSRState

LAYOUT = ZoneLayout(TEST_CLUSTER, grid_size=8 * 1024 * 1024)


# ----------------------------------------------------------------------
# checksum + header
# ----------------------------------------------------------------------


def test_checksum_reference_vectors():
    """The reference pins these (reference: src/vsr/checksum.zig:83-101,
    src/vsr.zig:238 checksum_body_empty)."""
    assert native.checksum(b"") == native.CHECKSUM_BODY_EMPTY
    exp16 = int.from_bytes(
        bytes.fromhex("f72ad48dd05dd1656133101cd4be3a26"), "little"
    )
    assert native.checksum(b"\x00" * 16) == exp16
    # pure function; sensitive to any flip
    data = os.urandom(1000)
    c = native.checksum(data)
    assert c == native.checksum(data)
    assert c != native.checksum(data[:-1] + bytes([data[-1] ^ 1]))


def test_header_roundtrip_and_checksums():
    h = Header(command=int(Command.prepare), operation=int(Operation.create_transfers),
               op=7, commit=6, timestamp=12345, parent=0xDEAD)
    body = b"x" * 256
    h.set_checksum_body(body)
    h.set_checksum()
    assert h.size == 128 + 256
    b = h.to_bytes()
    assert len(b) == 128
    h2 = Header.from_bytes(b)
    assert h2 == h
    assert h2.valid_checksum()
    assert h2.valid_checksum_body(body)
    assert not h2.valid_checksum_body(body[:-1] + b"y")
    # flip a byte in the header -> checksum fails
    bad = bytearray(b)
    bad[40] ^= 1
    assert not Header.from_bytes(bytes(bad)).valid_checksum()


# ----------------------------------------------------------------------
# journal
# ----------------------------------------------------------------------


def _prepare(op, body, parent=0):
    h = Header(
        command=int(Command.prepare),
        operation=int(Operation.create_transfers),
        op=op,
        parent=parent,
    )
    h.set_checksum_body(body)
    h.set_checksum()
    return h


def test_journal_write_read_recover():
    storage = MemoryStorage(LAYOUT)
    j = Journal(storage, TEST_CLUSTER)
    bodies = {op: bytes([op]) * (100 + op) for op in range(1, 6)}
    for op, body in bodies.items():
        j.write_prepare(_prepare(op, body), body)

    for op, body in bodies.items():
        got = j.read_prepare(op)
        assert got is not None
        assert got[1] == body

    # fresh journal over the same storage recovers all ops
    j2 = Journal(storage, TEST_CLUSTER)
    recovered = j2.recover()
    assert sorted(recovered.keys()) == list(bodies.keys())


def test_journal_ring_wrap_and_torn_prepare():
    storage = MemoryStorage(LAYOUT)
    j = Journal(storage, TEST_CLUSTER)
    n = TEST_CLUSTER.journal_slot_count
    for op in range(1, n + 10):  # wraps: ops 1..9 overwritten
        body = op.to_bytes(8, "little") * 16
        j.write_prepare(_prepare(op, body), body)
    rec = Journal(storage, TEST_CLUSTER).recover()
    assert min(rec.keys()) == 10 and max(rec.keys()) == n + 9

    # torn prepare write: corrupt the newest op's BODY (header byte range
    # [0,128) left intact, so only checksum_body catches it)
    slot = j.slot_for_op(n + 9)
    storage.fault(Zone.wal_prepares, slot * TEST_CLUSTER.message_size_max + 128, 128)
    rec = Journal(storage, TEST_CLUSTER).recover()
    assert n + 9 not in rec  # faulty slot detected by checksum
    assert n + 8 in rec


def test_journal_faulty_slot_preserved_across_neighbor_writes():
    """A torn prepare with an intact redundant header is recorded as faulty,
    and the redundant header survives a neighbor-slot header write (the
    4 KiB sector read-modify-write must not zero it)."""
    storage = MemoryStorage(LAYOUT)
    j = Journal(storage, TEST_CLUSTER)
    for op in (1, 2, 3):
        body = bytes([op]) * 64
        j.write_prepare(_prepare(op, body), body)
    # tear op 2's prepare body; redundant header remains valid
    slot = j.slot_for_op(2)
    storage.fault(Zone.wal_prepares, slot * TEST_CLUSTER.message_size_max + 128, 64)

    j2 = Journal(storage, TEST_CLUSTER)
    rec = j2.recover()
    assert sorted(rec) == [1, 3]
    assert j2.faulty == {slot: 2}

    # op 1 lives in the same header sector; rewriting it must not destroy
    # op 2's redundant header evidence
    body = b"z" * 64
    j2.write_prepare(_prepare(65, body), body)  # slot_for_op(65) == 1
    j3 = Journal(storage, TEST_CLUSTER)
    j3.recover()
    assert j3.faulty == {slot: 2}


def test_memory_storage_torn_write_crash():
    """crash() tears only the in-flight write, sector-independently."""
    storage = MemoryStorage(LAYOUT, seed=123)
    first = b"a" * SECTOR_SIZE
    storage.write(Zone.grid, 0, first)
    storage.write(Zone.grid, SECTOR_SIZE, b"b" * (4 * SECTOR_SIZE))
    storage.crash()
    # the first (acknowledged) write is untouched
    assert storage.read(Zone.grid, 0, SECTOR_SIZE) == first
    got = storage.read(Zone.grid, SECTOR_SIZE, 4 * SECTOR_SIZE)
    kept = sum(
        got[s : s + SECTOR_SIZE] == b"b" * SECTOR_SIZE
        for s in range(0, len(got), SECTOR_SIZE)
    )
    assert 0 <= kept < 4  # seed 123: at least one sector torn


# ----------------------------------------------------------------------
# superblock
# ----------------------------------------------------------------------


def test_superblock_checkpoint_open_quorum():
    storage = MemoryStorage(LAYOUT)
    sb = SuperBlock(storage)
    sb.checkpoint(VSRState(cluster=7, sequence=1))
    sb.checkpoint(VSRState(cluster=7, sequence=2, commit_min=42))

    sb2 = SuperBlock(storage)
    st = sb2.open()
    assert st.sequence == 2 and st.commit_min == 42 and st.cluster == 7

    # corrupt 2 of 4 copies -> still a quorum of 2
    storage.fault(Zone.superblock, 0, ZoneLayout.SUPERBLOCK_COPY_SIZE)
    storage.fault(
        Zone.superblock, ZoneLayout.SUPERBLOCK_COPY_SIZE,
        ZoneLayout.SUPERBLOCK_COPY_SIZE,
    )
    assert SuperBlock(storage).open().commit_min == 42

    # corrupt a third -> no quorum
    storage.fault(
        Zone.superblock, 2 * ZoneLayout.SUPERBLOCK_COPY_SIZE,
        ZoneLayout.SUPERBLOCK_COPY_SIZE,
    )
    with pytest.raises(RuntimeError, match="quorum"):
        SuperBlock(storage).open()


# ----------------------------------------------------------------------
# durable ledger: crash / recover / replay
# ----------------------------------------------------------------------


def _run_workload(target, gen, n_batches, batch_size=24, start=0):
    """Drive `target` (StateMachine-like submit API) with seeded batches.
    `start` continues the batch-kind schedule across split runs."""
    for b in range(start, start + n_batches):
        if b % 3 == 0:
            op, events = gen.gen_accounts_batch(batch_size)
            body = types.accounts_to_np(events).tobytes()
        else:
            op, events = gen.gen_transfers_batch(batch_size)
            body = types.transfers_to_np(events).tobytes()
        target(op, body)


def _oracle_after(n_batches, seed=77, batch_size=24):
    sm = StateMachine(OracleStateMachine(), TEST_CLUSTER)

    def submit(op, body):
        sm.prepare(op, body)
        sm.commit(op, sm.prepare_timestamp, body)

    _run_workload(submit, WorkloadGenerator(seed), n_batches, batch_size)
    return sm.backend


def test_durable_ledger_recovery_mid_epoch():
    """Crash AFTER a checkpoint with a WAL tail: recovery = snapshot +
    replay; state bit-identical to an uninterrupted run."""
    storage = MemoryStorage(LAYOUT)
    format_data_file(storage, TEST_CLUSTER)

    dl = DurableLedger(storage, TEST_CLUSTER, TEST_PROCESS)
    dl.open()
    gen = WorkloadGenerator(77)
    _run_workload(dl.submit, gen, 5)
    dl.checkpoint()
    _run_workload(dl.submit, gen, 4, start=5)  # WAL tail beyond the checkpoint
    assert dl.op == 9

    # "crash": new process objects over the same storage bytes
    dl2 = DurableLedger(storage, TEST_CLUSTER, TEST_PROCESS)
    dl2.open()
    assert dl2.op == 9
    assert dl2.parent_checksum == dl.parent_checksum

    oracle = _oracle_after(9)
    accounts, transfers, posted = dl2.ledger.extract()
    assert accounts == oracle.accounts
    assert transfers == oracle.transfers
    assert posted == oracle.posted
    assert dl2.sm.prepare_timestamp == oracle.prepare_timestamp

    # and the recovered ledger keeps serving writes
    _run_workload(dl2.submit, gen, 2, start=9)
    assert dl2.op == 11


def test_durable_ledger_checkpoint_ordering_crash_between():
    """Crash BETWEEN snapshot-blob writes and the superblock update: the old
    superblock must still open against the previous snapshot (ping-pong
    areas), replaying the full WAL tail."""
    storage = MemoryStorage(LAYOUT)
    format_data_file(storage, TEST_CLUSTER)
    dl = DurableLedger(storage, TEST_CLUSTER, TEST_PROCESS)
    dl.open()
    gen = WorkloadGenerator(77)
    _run_workload(dl.submit, gen, 5)
    dl.checkpoint()  # sequence 2, area 0
    _run_workload(dl.submit, gen, 4, start=5)

    # simulate: blobs of the NEXT checkpoint (the other ping-pong area)
    # written, superblock not
    area = (1 - dl.superblock.state.area) * storage.layout.snapshot_area_size
    storage.write(Zone.grid, area, b"\xAA" * 4096)  # garbage partial blobs

    dl2 = DurableLedger(storage, TEST_CLUSTER, TEST_PROCESS)
    dl2.open()
    oracle = _oracle_after(9)
    accounts, transfers, posted = dl2.ledger.extract()
    assert accounts == oracle.accounts
    assert transfers == oracle.transfers
    assert posted == oracle.posted


def test_durable_ledger_snapshot_corruption_detected():
    storage = MemoryStorage(LAYOUT)
    format_data_file(storage, TEST_CLUSTER)
    dl = DurableLedger(storage, TEST_CLUSTER, TEST_PROCESS)
    dl.open()
    gen = WorkloadGenerator(5)
    _run_workload(dl.submit, gen, 3)
    dl.checkpoint()
    ref = dl.superblock.state.blobs[0]
    storage.fault(Zone.grid, ref.offset)
    dl2 = DurableLedger(storage, TEST_CLUSTER, TEST_PROCESS)
    with pytest.raises(RuntimeError, match="checksum"):
        dl2.open()


CHILD_SCRIPT = r"""
import os, sys
sys.path.insert(0, {repo!r})
import tests.conftest  # force the CPU platform before jax init
from tigerbeetle_tpu import types
from tigerbeetle_tpu.constants import TEST_CLUSTER, TEST_PROCESS
from tigerbeetle_tpu.io.storage import FileStorage, ZoneLayout
from tigerbeetle_tpu.testing.workload import WorkloadGenerator
from tigerbeetle_tpu.vsr.durable import DurableLedger, format_data_file

layout = ZoneLayout(TEST_CLUSTER, grid_size=8 * 1024 * 1024)
path = {path!r}
storage = FileStorage(path, layout, create=True)
format_data_file(storage, TEST_CLUSTER)
dl = DurableLedger(storage, TEST_CLUSTER, TEST_PROCESS)
dl.open()
gen = WorkloadGenerator(77)
n = 0
for b in range(9):
    if b % 3 == 0:
        op, events = gen.gen_accounts_batch(24)
        body = types.accounts_to_np(events).tobytes()
    else:
        op, events = gen.gen_transfers_batch(24)
        body = types.transfers_to_np(events).tobytes()
    dl.submit(op, body)
    n += 1
    if b == 4:
        dl.checkpoint()
    if b == 7:
        print(n, flush=True)
        os._exit(9)  # hard kill mid-stream: no atexit, no flush, no close
"""


def test_durable_ledger_process_kill_and_restart(tmp_path):
    """A real child process dies (os._exit, no cleanup) mid-stream; a fresh
    process recovers from the file and matches the oracle bit-for-bit."""
    path = str(tmp_path / "data.tigerbeetle")
    script = CHILD_SCRIPT.format(repo=os.path.dirname(os.path.dirname(__file__)),
                                 path=path)
    env = dict(os.environ)
    proc = subprocess.run(
        [sys.executable, "-c", script], capture_output=True, text=True,
        env=env, timeout=600,
    )
    assert proc.returncode == 9, proc.stderr[-2000:]
    committed = int(proc.stdout.strip().splitlines()[-1])
    assert committed == 8

    from tigerbeetle_tpu.io.storage import FileStorage

    storage = FileStorage(path, LAYOUT)
    dl = DurableLedger(storage, TEST_CLUSTER, TEST_PROCESS)
    dl.open()
    assert dl.op == committed
    oracle = _oracle_after(committed)
    accounts, transfers, posted = dl.ledger.extract()
    assert accounts == oracle.accounts
    assert transfers == oracle.transfers
    assert posted == oracle.posted
    storage.close()
