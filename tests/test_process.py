"""Real-process integration (reference: src/testing/tmp_tigerbeetle.zig +
client integration tests): spawn the server binary, drive it over real TCP
with the native C client and the REPL, kill it, restart it, verify
durability."""

import os
import signal
import socket
import subprocess
import sys

import pytest

from tigerbeetle_tpu.types import (
    Account,
    CreateTransferResult,
    Transfer,
    TransferFlags,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _spawn_server(path: str, port: int, aof: str | None = None):
    cmd = [
        sys.executable, "-m", "tigerbeetle_tpu", "start",
        "--addresses", f"127.0.0.1:{port}",
        "--grid-mb", "8",
        "--account-slots-log2", "10",
        "--transfer-slots-log2", "12",
    ]
    if aof:
        cmd += ["--aof", aof]
    cmd.append(path)
    env = dict(os.environ, TB_JAX_PLATFORM="cpu", PYTHONPATH=REPO,
               TB_PARENT_WATCHDOG="1")
    proc = subprocess.Popen(
        cmd, cwd=REPO, env=env, start_new_session=True,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )
    line = proc.stdout.readline()  # blocks until "listening" (or crash)
    if "listening" not in line:
        rest = proc.stdout.read()
        _kill_group(proc)
        raise AssertionError(f"server failed to start: {line}{rest}")
    return proc


def _kill_group(proc) -> None:
    """Kill the server's whole process group (spawned with
    start_new_session=True, so pgid == pid) and reap it; leaked servers
    from partial teardowns used to survive the suite and burn CPU."""
    from tigerbeetle_tpu.benchmark import kill_process_group

    kill_process_group(proc)
    try:
        proc.wait(timeout=10)
    except Exception:
        pass


@pytest.fixture(scope="module")
def server(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("proc")
    path = str(tmp / "data.tigerbeetle")
    aof = str(tmp / "data.aof")
    port = _free_port()
    fmt = subprocess.run(
        [sys.executable, "-m", "tigerbeetle_tpu", "format",
         "--cluster", "0", "--replica", "0", "--replica-count", "1",
         "--grid-mb", "8", path],
        cwd=REPO, env=dict(os.environ, PYTHONPATH=REPO),
        capture_output=True, text=True, timeout=120,
    )
    assert fmt.returncode == 0, fmt.stderr
    proc = _spawn_server(path, port, aof=aof)
    state = {"proc": proc, "path": path, "port": port, "aof": aof}
    yield state
    _kill_group(state["proc"])  # the kill/restart test replaces "proc"


def test_native_client_end_to_end(server):
    from tigerbeetle_tpu.client_ffi import NativeClient

    client = NativeClient("127.0.0.1", server["port"])
    assert client.create_accounts(
        [Account(id=i, ledger=1, code=1) for i in (1, 2, 3)]
    ) == []
    results = client.create_transfers([
        Transfer(id=10, debit_account_id=1, credit_account_id=2, amount=100,
                 ledger=1, code=1),
        Transfer(id=11, debit_account_id=1, credit_account_id=3, amount=50,
                 ledger=1, code=1, flags=int(TransferFlags.pending)),
        Transfer(id=12, pending_id=11,
                 flags=int(TransferFlags.post_pending_transfer)),
        Transfer(id=13, debit_account_id=1, credit_account_id=1, amount=5,
                 ledger=1, code=1),
    ])
    assert results == [(3, int(CreateTransferResult.accounts_must_be_different))]
    accounts = client.lookup_accounts([1, 2, 3, 404])
    assert len(accounts) == 3
    assert accounts[0].debits_posted == 150 and accounts[0].debits_pending == 0
    transfers = client.lookup_transfers([12])
    assert transfers[0].amount == 50 and transfers[0].pending_id == 11
    client.close()


def test_repl_against_live_server(server):

    from tigerbeetle_tpu.repl import Repl, parse_statement
    from tigerbeetle_tpu.types import Operation

    op, events = parse_statement(
        "create_transfers id=77 debit_account_id=2 credit_account_id=3 "
        "amount=7 ledger=1 code=1;"
    )
    assert op == Operation.create_transfers and events[0].amount == 7

    repl = Repl([("127.0.0.1", server["port"])])
    repl.connect()
    out = repl.execute(*parse_statement(
        "create_accounts id=500 ledger=1 code=9;"
    ))
    assert out == "ok"
    out = repl.execute(*parse_statement("lookup_accounts id=500;"))
    assert "id=500" in out and "code=9" in out
    out = repl.execute(*parse_statement("create_accounts id=500 ledger=1 code=8;"))
    assert "exists_with_different_code" in out


def test_three_replica_tcp_cluster(tmp_path):
    """Three real server processes over real sockets: consensus across
    OS process boundaries, driven by the native C client."""
    from tigerbeetle_tpu.client_ffi import NativeClient

    ports = [_free_port() for _ in range(3)]
    addresses = ",".join(f"127.0.0.1:{p}" for p in ports)
    procs = []
    try:
        for i in range(3):
            path = str(tmp_path / f"r{i}.tigerbeetle")
            fmt = subprocess.run(
                [sys.executable, "-m", "tigerbeetle_tpu", "format",
                 "--cluster", "0", "--replica", str(i),
                 "--replica-count", "3", "--grid-mb", "8", path],
                cwd=REPO, env=dict(os.environ, PYTHONPATH=REPO),
                capture_output=True, text=True, timeout=120,
            )
            assert fmt.returncode == 0, fmt.stderr
        for i in range(3):
            cmd = [
                sys.executable, "-m", "tigerbeetle_tpu", "start",
                "--addresses", addresses, "--replica", str(i),
                "--grid-mb", "8", "--account-slots-log2", "10",
                "--transfer-slots-log2", "12",
                str(tmp_path / f"r{i}.tigerbeetle"),
            ]
            env = dict(os.environ, TB_JAX_PLATFORM="cpu", PYTHONPATH=REPO,
               TB_PARENT_WATCHDOG="1")
            p = subprocess.Popen(cmd, cwd=REPO, env=env,
                                 start_new_session=True,
                                 stdout=subprocess.PIPE,
                                 stderr=subprocess.STDOUT, text=True)
            procs.append(p)
            line = p.stdout.readline()
            assert "listening" in line, line + (p.stdout.read() or "")

        client = NativeClient(addresses)  # rotates to find the primary
        assert client.create_accounts(
            [Account(id=i, ledger=1, code=1) for i in (1, 2)]
        ) == []
        assert client.create_transfers([
            Transfer(id=10, debit_account_id=1, credit_account_id=2,
                     amount=42, ledger=1, code=1)
        ]) == []
        accounts = client.lookup_accounts([1, 2])
        assert accounts[0].debits_posted == 42
        assert accounts[1].credits_posted == 42
        client.close()
    finally:
        for p in procs:
            _kill_group(p)


def test_statsd_and_tracer_units(tmp_path):
    import json as _json
    import socket as _socket

    from tigerbeetle_tpu.statsd import StatsD
    from tigerbeetle_tpu.tracer import JsonTracer, Tracer

    # statsd: packets really hit the wire in the documented format
    sink = _socket.socket(_socket.AF_INET, _socket.SOCK_DGRAM)
    sink.bind(("127.0.0.1", 0))
    sink.settimeout(2)
    port = sink.getsockname()[1]
    s = StatsD("127.0.0.1", port, prefix="tb")
    s.count("ops", 3)
    s.gauge("commit", 17)
    s.timing("batch", 1.5)
    got = {sink.recv(256).decode() for _ in range(3)}
    assert got == {"tb.ops:3|c", "tb.commit:17|g", "tb.batch:1.5|ms"}
    s.close()
    sink.close()

    # tracer: spans nest and dump as Chrome trace events
    tr = JsonTracer()
    with tr.span("commit", op=7):
        with tr.span("prefetch"):
            pass
    path = str(tmp_path / "trace.json")
    tr.dump(path)
    events = _json.load(open(path))["traceEvents"]
    assert {e["name"] for e in events} == {"commit", "prefetch"}
    assert all(e["ph"] == "X" and e["dur"] >= 0 for e in events)
    # the none backend is a no-op
    with Tracer().span("x"):
        pass


def test_c_example_client_against_live_server(server):
    """Compile and run the pure-C example program (no Python anywhere in the
    client path): the C ABI + wire protocol end to end."""
    native_dir = os.path.join(REPO, "native")
    exe = os.path.join(native_dir, "example_client")
    cc = subprocess.run(
        ["gcc", "-O2", "-o", exe, "example_client.c",
         # -lpthread explicitly: libtb_native.so uses pthreads and some
         # toolchains do not resolve transitive shared-lib deps
         "-L.", "-ltb_native", "-lpthread", "-Wl,-rpath," + native_dir],
        cwd=native_dir, capture_output=True, text=True,
    )
    assert cc.returncode == 0, cc.stderr
    run = subprocess.run(
        [exe, f"127.0.0.1:{server['port']}"],
        capture_output=True, text=True, timeout=300,
    )
    assert run.returncode == 0, run.stdout + run.stderr
    # ids 1,2 already exist from earlier tests in this module: the creates
    # report result codes; the transfer and lookups still round-trip
    assert "transfer: ok" in run.stdout
    assert "account 901:" in run.stdout and "account 902:" in run.stdout


def test_kill_restart_durability_and_aof(server):
    from tigerbeetle_tpu import aof as aof_mod
    from tigerbeetle_tpu.client_ffi import NativeClient
    from tigerbeetle_tpu.types import Operation

    proc = server["proc"]
    proc.send_signal(signal.SIGKILL)  # hard kill, no cleanup
    proc.wait(timeout=30)

    # AOF alone can reconstruct the committed history
    ops = list(aof_mod.replay(server["aof"]))
    assert len(ops) >= 3
    assert {Operation(h.operation) for h, _ in ops} >= {
        Operation.create_accounts, Operation.create_transfers
    }

    proc2 = _spawn_server(server["path"], server["port"], aof=server["aof"] + "2")
    server["proc"] = proc2
    client = NativeClient("127.0.0.1", server["port"])
    accounts = client.lookup_accounts([1, 500])
    assert accounts[0].debits_posted == 150  # survived the kill
    assert accounts[1].code == 9
    # and the restarted server still serves writes
    assert client.create_accounts([Account(id=600, ledger=1, code=1)]) == []
    client.close()
