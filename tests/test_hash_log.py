"""hash_log record/check (reference: src/testing/hash_log.zig): identical
runs replay hash-for-hash; an injected nondeterminism is caught AT the
first divergent op, on the prepare stream (log divergence) or the reply
stream (execution divergence with an identical log)."""

import pytest

from tigerbeetle_tpu import types
from tigerbeetle_tpu.testing.cluster import Cluster
from tigerbeetle_tpu.testing.hash_log import HashLog, HashLogDivergence
from tigerbeetle_tpu.testing.workload import WorkloadGenerator


def _run(log: HashLog, tamper_batch: int | None = None) -> None:
    cluster = Cluster(replica_count=1)
    log.attach(cluster.replicas[0])
    client = cluster.add_client()
    gen = WorkloadGenerator(33)
    for b in range(5):
        if b % 2 == 0:
            op, events = gen.gen_accounts_batch(10)
            body = types.accounts_to_np(events).tobytes()
        else:
            op, events = gen.gen_transfers_batch(10)
            arr = types.transfers_to_np(events)
            if tamper_batch == b:
                arr["amount_lo"][3] += 1  # the injected nondeterminism
            body = arr.tobytes()
        cluster.execute(client, op, body)


def test_identical_runs_check_clean(tmp_path):
    path = str(tmp_path / "run.hashlog")
    rec = HashLog("record")
    _run(rec)
    rec.save(path)
    chk = HashLog("check", path)
    _run(chk)  # raises on any divergence
    assert chk.digest() == rec.digest()


def test_injected_divergence_caught_at_first_op(tmp_path):
    path = str(tmp_path / "run.hashlog")
    rec = HashLog("record")
    _run(rec)
    rec.save(path)
    chk = HashLog("check", path)
    with pytest.raises(HashLogDivergence) as e:
        _run(chk, tamper_batch=3)
    # batch 3 is the 4th request; op 1 is the session register -> op 5
    assert e.value.op == 5
    assert e.value.kind == "prepare"  # body changed -> log diverges


def test_parse_hash_log_spec():
    from tigerbeetle_tpu.testing.hash_log import parse_hash_log_spec

    assert parse_hash_log_spec("record:/tmp/x.jsonl") == (
        "record", "/tmp/x.jsonl"
    )
    assert parse_hash_log_spec("check:/tmp/x.jsonl") == (
        "check", "/tmp/x.jsonl"
    )
    # bare path records; a path with a colon elsewhere stays intact
    assert parse_hash_log_spec("/tmp/x.jsonl") == ("record", "/tmp/x.jsonl")


def test_simulator_hash_log_record_then_check(tmp_path):
    """The vopr/simulator surface (satellite wiring): a seed RECORDS its
    committed prepare/reply checksum stream; the same seed CHECKS clean;
    a tampered recording fails the replay at its exact op — hash-log
    debugging outside the bench harness."""
    import json

    from tigerbeetle_tpu.testing.simulator import run_simulation

    path = str(tmp_path / "seed9.jsonl")
    stats = run_simulation(9, ticks=250, hash_log=("record", path))
    assert stats["hash_log_mode"] == "record"
    assert stats["hash_log_ops"] >= 1
    # same seed, check mode: replays hash-for-hash
    stats2 = run_simulation(9, ticks=250, hash_log=("check", path))
    assert stats2["hash_log_ops"] == stats["hash_log_ops"]
    # tamper one recorded prepare hash -> the replay dies AT that op
    lines = [json.loads(x) for x in open(path)]
    victim = lines[len(lines) // 2]
    victim["prepare"] = hex(int(victim["prepare"], 16) ^ 1)
    with open(path, "w") as f:
        for rec in lines:
            f.write(json.dumps(rec) + "\n")
    with pytest.raises(HashLogDivergence) as e:
        run_simulation(9, ticks=250, hash_log=("check", path))
    assert e.value.op == int(victim["op"])


def test_reply_stream_catches_execution_divergence(tmp_path):
    """Same LOG, different results: simulate a kernel nondeterminism by
    checking a recording whose reply hash was corrupted — the prepare
    stream stays clean, the reply stream trips."""
    path = str(tmp_path / "run.hashlog")
    rec = HashLog("record")
    _run(rec)
    # corrupt op 5's recorded REPLY hash only
    rec.entries[5][1] ^= 1
    rec.save(path)
    chk = HashLog("check", path)
    with pytest.raises(HashLogDivergence) as e:
        _run(chk)
    assert e.value.op == 5
    assert e.value.kind == "reply"
