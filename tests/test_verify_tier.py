"""The intensive online-verification tier (constants.VERIFY — reference:
src/constants.zig:592 `constants.verify` compiles extra invariant checks
into hot paths) and the randomized VOPR fleet that exercises it.

Covers: the tier's checks pass on healthy runs (simulator seed, LSM
compaction churn, journal writes), each check actually FIRES on a broken
invariant, and the fleet's seed-derived topology draw is deterministic.
"""

import numpy as np
import pytest

from tigerbeetle_tpu import constants, types
from tigerbeetle_tpu.constants import TEST_CLUSTER
from tigerbeetle_tpu.io.storage import MemoryStorage, ZoneLayout
from tigerbeetle_tpu.lsm.grid import Grid
from tigerbeetle_tpu.lsm.tree import Tree
from tigerbeetle_tpu.models.oracle import OracleStateMachine
from tigerbeetle_tpu.testing.simulator import (
    describe_options,
    random_options,
    run_simulation,
)


@pytest.fixture
def verify_on():
    prev, constants.VERIFY = constants.VERIFY, True
    yield
    constants.VERIFY = prev


def _tree(memtable_max=64):
    layout = ZoneLayout(TEST_CLUSTER, grid_size=64 * 1024 * 1024)
    grid = Grid(MemoryStorage(layout), offset=0, block_count=448,
                cache_blocks=32)
    return Tree(grid, key_size=8, value_size=8, memtable_max=memtable_max)


def test_lsm_level_audit_green_under_churn(verify_on):
    """Enough puts to drive multi-level compaction with the audit live."""
    t = _tree()
    rng = np.random.default_rng(3)
    for i in range(4000):
        t.put(int(rng.integers(0, 1 << 48)).to_bytes(8, "big"),
              i.to_bytes(8, "little"))
        if i % 250 == 249:
            # the checkpoint-cadence free-set apply: compaction's staged
            # releases become reusable (grid.py contract)
            t.grid.encode_free_set()
    t.flush()
    t.verify_levels()


def test_lsm_level_audit_fires_on_overlap(verify_on):
    t = _tree()
    for i in range(300):
        t.put(i.to_bytes(8, "big"), i.to_bytes(8, "little"))
    t.flush()
    # corrupt: force an overlapping pair into a deep level
    deep = [lvl for lvl in t.levels[1:] if len(lvl) >= 1]
    assert deep, "churn did not reach level 1 - grow the workload"
    info = deep[0][0]
    clone = type(info)(
        index_address=info.index_address, key_min=info.key_min,
        key_max=info.key_max, entry_count=info.entry_count,
    )
    deep[0].append(clone)  # same range twice = overlap
    with pytest.raises(AssertionError, match="overlap"):
        t.verify_levels()


def test_oracle_conservation_audit(verify_on):
    o = OracleStateMachine()
    ts = 1 << 41
    o.execute(types.Operation.create_accounts, ts + 2, [
        types.Account(id=1, ledger=1, code=1),
        types.Account(id=2, ledger=1, code=1),
    ])
    o.execute(types.Operation.create_transfers, ts + 3, [
        types.Transfer(id=9, debit_account_id=1, credit_account_id=2,
                       amount=50, ledger=1, code=1),
    ])
    o.verify_conservation()
    # corrupt one balance: the audit must fire
    o.accounts[1].debits_posted += 7
    with pytest.raises(AssertionError, match="conservation"):
        o.verify_conservation()


def test_journal_read_after_write_verify(verify_on):
    """A healthy write passes the read-after-write check; a storage that
    drops the write fails it."""
    from tigerbeetle_tpu.vsr.durable import format_data_file
    from tigerbeetle_tpu.vsr.header import Command, Header
    from tigerbeetle_tpu.vsr.journal import Journal

    layout = ZoneLayout(TEST_CLUSTER)
    storage = MemoryStorage(layout)
    format_data_file(storage, TEST_CLUSTER)
    j = Journal(storage, TEST_CLUSTER)
    body = b"x" * 128
    h = Header(command=int(Command.prepare), op=1, size=128 + len(body),
               operation=int(types.Operation.create_accounts),
               timestamp=1 << 41)
    h.set_checksum_body(body)
    h.set_checksum()
    j.write_prepare(h, body)  # green path

    class DroppingStorage:
        def __getattr__(self, name):
            return getattr(storage, name)

        def write(self, zone, off, data):
            pass  # lost write

    j2 = Journal(DroppingStorage(), TEST_CLUSTER)
    h2 = Header(command=int(Command.prepare), op=2, size=128 + len(body),
                operation=int(types.Operation.create_accounts),
                timestamp=(1 << 41) + 1)
    h2.set_checksum_body(body)
    h2.set_checksum()
    with pytest.raises(AssertionError, match="read-after-write"):
        j2.write_prepare(h2, body)


def test_simulator_seed_with_verify(verify_on):
    stats = run_simulation(5, ticks=400)
    assert stats["committed_ops"] > 0


# -- the randomized fleet draw (scripts/vopr.py; reference:
#    src/simulator.zig:66-152) --

def test_random_options_deterministic_and_in_range():
    for seed in range(1, 60):
        a = random_options(seed)
        b = random_options(seed)
        assert describe_options(a) == describe_options(b)
        assert 1 <= a["replica_count"] <= 6
        assert 0 <= a["standby_count"] <= 2
        assert 1 <= a["n_clients"] <= 8
        assert 0.0 <= a["wal_fault_probability"] <= 0.35
        assert 0.0 <= a["torn_write_probability"] <= 0.35


def test_random_options_device_slice():
    draws = [random_options(s, device_fraction=0.5) for s in range(1, 40)]
    device = [d for d in draws if d.get("backend_factory", "x") is None]
    assert device, "device slice never drawn at fraction 0.5"
    for d in device:
        assert d["grid_fault_probability"] > 0
        assert d["forest_blocks"] > 0
        # grid-fault atlas needs a peer copy
        assert d["replica_count"] >= 2


def test_random_topology_seeds_green():
    """A small randomized-fleet batch in CI: every seed must pass the
    simulator's checkers (one linear history, convergence, oracle parity)
    under its drawn topology + fault mix."""
    for seed in (101, 102, 103):
        opts = random_options(seed)
        stats = run_simulation(seed, ticks=400, **opts)
        assert stats["committed_ops"] > 0, (seed, stats)
