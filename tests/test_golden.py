"""Golden vectors: the reference's OWN state-machine test tables, transcribed
verbatim and replayed against our oracle (VERDICT round-1 item 8 — pins the
oracle to the Zig semantics, not to our reading of them).

Source tables: reference src/state_machine.zig:1531-2075 (the TestAction DSL,
:1247-1299; table syntax from src/testing/table.zig). Value conventions:
`A1`/`T1`/`U1`/`L1`/`C1`/`P1` are numeric with a type tag; `_` is zero/absent;
`-N` is maxInt-N for the column's integer width; flags columns hold the flag
mnemonic or `_`.

Tables without raw-balance `setup` rows also replay against the DEVICE ledger
(auto tier dispatch), so the golden vectors pin the TPU kernels as well.
"""

import pytest

from tigerbeetle_tpu.constants import TEST_PROCESS, U64_MAX, U128_MAX
from tigerbeetle_tpu.models.ledger import DeviceLedger
from tigerbeetle_tpu.models.oracle import OracleStateMachine
from tigerbeetle_tpu.types import (
    Account,
    AccountFlags,
    CreateAccountResult,
    CreateTransferResult,
    Operation,
    Transfer,
    TransferFlags,
)

MAX128 = U128_MAX


def _num(tok: str, width_max: int = MAX128) -> int:
    if tok == "_":
        return 0
    if tok[0] in "ATULCP" and tok[1:].lstrip("-").isdigit():
        tok = tok[1:]
    if tok.startswith("-"):
        return width_max - int(tok[1:])
    return int(tok)


def _account_row(toks: list[str]) -> tuple[Account, str]:
    # id dp dpo cp cpo U128 U64 U32 reserved L C LNK D<C C<D padding ts result
    assert len(toks) == 17, toks
    flags = 0
    if toks[11] == "LNK":
        flags |= AccountFlags.linked
    if toks[12] == "D<C":
        flags |= AccountFlags.debits_must_not_exceed_credits
    if toks[13] == "C<D":
        flags |= AccountFlags.credits_must_not_exceed_debits
    flags |= _num(toks[14]) << 3  # padding bits
    a = Account(
        id=_num(toks[0]),
        debits_pending=_num(toks[1]), debits_posted=_num(toks[2]),
        credits_pending=_num(toks[3]), credits_posted=_num(toks[4]),
        user_data_128=_num(toks[5]), user_data_64=_num(toks[6], U64_MAX),
        user_data_32=_num(toks[7], (1 << 32) - 1),
        reserved=_num(toks[8], (1 << 32) - 1),
        ledger=_num(toks[9], (1 << 32) - 1), code=_num(toks[10], (1 << 16) - 1),
        flags=int(flags), timestamp=_num(toks[15], U64_MAX),
    )
    return a, toks[16]


def _transfer_row(toks: list[str]) -> tuple[Transfer, str]:
    # id dr cr amount pending U128 U64 U32 timeout L C
    # LNK PEN POS VOI BDR BCR padding ts result
    assert len(toks) == 20, toks
    flags = 0
    for i, (mn, bit) in enumerate([
        ("LNK", TransferFlags.linked), ("PEN", TransferFlags.pending),
        ("POS", TransferFlags.post_pending_transfer),
        ("VOI", TransferFlags.void_pending_transfer),
        ("BDR", TransferFlags.balancing_debit),
        ("BCR", TransferFlags.balancing_credit),
    ]):
        if toks[11 + i] == mn:
            flags |= bit
    flags |= _num(toks[17]) << 6  # padding bits
    t = Transfer(
        id=_num(toks[0]), debit_account_id=_num(toks[1]),
        credit_account_id=_num(toks[2]), amount=_num(toks[3]),
        pending_id=_num(toks[4]), user_data_128=_num(toks[5]),
        user_data_64=_num(toks[6], U64_MAX),
        user_data_32=_num(toks[7], (1 << 32) - 1),
        timeout=_num(toks[8], (1 << 32) - 1),
        ledger=_num(toks[9], (1 << 32) - 1), code=_num(toks[10], (1 << 16) - 1),
        flags=int(flags), timestamp=_num(toks[18], U64_MAX),
    )
    return t, toks[19]


def run_table(table: str, device: bool = False, backend=None) -> None:
    """Replay one reference test table. With device=True the ledger under
    test is the TPU kernel stack (oracle still drives lookups of raw state
    expectations); `backend` swaps in any other ledger backend with the
    same duck-typed API (the native C++ engine)."""
    oracle = OracleStateMachine()
    if backend is not None:
        dev = backend()
    else:
        dev = DeviceLedger(process=TEST_PROCESS, mode="auto") if device else None

    pending: list = []
    expected: list[str] = []
    lookups: list[tuple] = []

    def reset():
        pending.clear()
        expected.clear()
        lookups.clear()

    for raw in table.splitlines():
        line = raw.split("//")[0].strip()
        if not line:
            continue
        toks = line.split()
        kind, toks = toks[0], toks[1:]
        if kind == "account":
            a, result = _account_row(toks)
            pending.append(a)
            expected.append(result)
        elif kind == "transfer":
            t, result = _transfer_row(toks)
            pending.append(t)
            expected.append(result)
        elif kind == "setup":
            assert not device, "setup tables run oracle-only"
            a = oracle.accounts[_num(toks[0])]
            a.debits_pending = _num(toks[1])
            a.debits_posted = _num(toks[2])
            a.credits_pending = _num(toks[3])
            a.credits_posted = _num(toks[4])
        elif kind == "tick":
            delta = _num(toks[0], U64_MAX)
            oracle.prepare_timestamp = (oracle.prepare_timestamp + delta) % (
                U64_MAX + 1
            )
            if dev is not None:
                dev.prepare_timestamp = oracle.prepare_timestamp
        elif kind == "lookup_account":
            if len(toks) == 2 and toks[1] == "_":
                lookups.append(("account", _num(toks[0]), None))
            else:
                lookups.append(
                    ("account", _num(toks[0]), [_num(x) for x in toks[1:5]])
                )
        elif kind == "lookup_transfer":
            ident = _num(toks[0])
            if toks[1] == "exists":
                lookups.append(("transfer_exists", ident, toks[2] == "true"))
            else:
                assert toks[1] == "amount"
                lookups.append(("transfer_amount", ident, _num(toks[2])))
        elif kind == "commit":
            op = Operation[toks[0]]
            if op in (Operation.create_accounts, Operation.create_transfers):
                enum = (
                    CreateAccountResult
                    if op == Operation.create_accounts
                    else CreateTransferResult
                )
                oracle.prepare(op, len(pending))
                ts = oracle.prepare_timestamp
                dense = oracle.execute_dense(op, ts, list(pending))
                got = [enum(c).name for c in dense]
                assert got == expected, (
                    f"{op.name}: {list(zip(got, expected))}"
                )
                if dev is not None:
                    dev.prepare(op, len(pending))
                    assert dev.prepare_timestamp == ts
                    assert dev.execute_dense(op, ts, list(pending)) == dense
            else:
                for what, ident, expect in lookups:
                    if what == "account":
                        a = oracle.accounts.get(ident)
                        if expect is None:
                            assert a is None, f"A{ident} should not exist"
                        else:
                            assert a is not None, f"A{ident} missing"
                            got4 = [a.debits_pending, a.debits_posted,
                                    a.credits_pending, a.credits_posted]
                            assert got4 == expect, (ident, got4, expect)
                        if dev is not None:
                            found = dev.lookup_accounts([ident])
                            if expect is None:
                                assert found == []
                            else:
                                assert found and found[0] == a
                    elif what == "transfer_exists":
                        assert (ident in oracle.transfers) == expect, ident
                        if dev is not None:
                            assert bool(dev.lookup_transfers([ident])) == expect
                    else:  # transfer_amount
                        t = oracle.transfers[ident]
                        assert t.amount == expect, (ident, t.amount, expect)
                        if dev is not None:
                            assert dev.lookup_transfers([ident])[0] == t
            reset()
    assert not pending and not lookups, "table must end each batch with commit"


# ----------------------------------------------------------------------
# reference src/state_machine.zig:1531 "create_accounts"
# ----------------------------------------------------------------------

T_CREATE_ACCOUNTS = """
 account A1  0  0  0  0 U2 U2 U2 _ L3 C4 _   _   _ _ _ ok
 account A0  1  1  1  1  _  _  _ 1 L0 C0 _ D<C C<D 1 1 timestamp_must_be_zero
 account A0  1  1  1  1  _  _  _ 1 L0 C0 _ D<C C<D 1 _ reserved_field
 account A0  1  1  1  1  _  _  _ _ L0 C0 _ D<C C<D 1 _ reserved_flag
 account A0  1  1  1  1  _  _  _ _ L0 C0 _ D<C C<D _ _ id_must_not_be_zero
 account -0  1  1  1  1  _  _  _ _ L0 C0 _ D<C C<D _ _ id_must_not_be_int_max
 account A1  1  1  1  1 U1 U1 U1 _ L0 C0 _ D<C C<D _ _ flags_are_mutually_exclusive
 account A1  1  1  1  1 U1 U1 U1 _ L9 C9 _ D<C   _ _ _ debits_pending_must_be_zero
 account A1  0  1  1  1 U1 U1 U1 _ L9 C9 _ D<C   _ _ _ debits_posted_must_be_zero
 account A1  0  0  1  1 U1 U1 U1 _ L9 C9 _ D<C   _ _ _ credits_pending_must_be_zero
 account A1  0  0  0  1 U1 U1 U1 _ L9 C9 _ D<C   _ _ _ credits_posted_must_be_zero
 account A1  0  0  0  0 U1 U1 U1 _ L0 C0 _ D<C   _ _ _ ledger_must_not_be_zero
 account A1  0  0  0  0 U1 U1 U1 _ L9 C0 _ D<C   _ _ _ code_must_not_be_zero
 account A1  0  0  0  0 U1 U1 U1 _ L9 C9 _ D<C   _ _ _ exists_with_different_flags
 account A1  0  0  0  0 U1 U1 U1 _ L9 C9 _   _ C<D _ _ exists_with_different_flags
 account A1  0  0  0  0 U1 U1 U1 _ L9 C9 _   _   _ _ _ exists_with_different_user_data_128
 account A1  0  0  0  0 U2 U1 U1 _ L9 C9 _   _   _ _ _ exists_with_different_user_data_64
 account A1  0  0  0  0 U2 U2 U1 _ L9 C9 _   _   _ _ _ exists_with_different_user_data_32
 account A1  0  0  0  0 U2 U2 U2 _ L9 C9 _   _   _ _ _ exists_with_different_ledger
 account A1  0  0  0  0 U2 U2 U2 _ L3 C9 _   _   _ _ _ exists_with_different_code
 account A1  0  0  0  0 U2 U2 U2 _ L3 C4 _   _   _ _ _ exists
 commit create_accounts

 lookup_account -0 _
 lookup_account A0 _
 lookup_account A1 0 0 0 0
 lookup_account A2 _
 commit lookup_accounts
"""

# reference :1570 "linked accounts" (both tables)
T_LINKED_ACCOUNTS_1 = """
 account A7  0  0  0  0  _  _  _ _ L1 C1   _   _   _ _ _ ok
 account A1  0  0  0  0  _  _  _ _ L1 C1 LNK   _   _ _ _ linked_event_failed
 account A2  0  0  0  0  _  _  _ _ L1 C1 LNK   _   _ _ _ linked_event_failed
 account A1  0  0  0  0  _  _  _ _ L1 C1 LNK   _   _ _ _ exists
 account A3  0  0  0  0  _  _  _ _ L1 C1   _   _   _ _ _ linked_event_failed
 account A1 0 0 0 0 _ _ _ _ L1 C1   _ _ _ _ _ ok
 account A1  0  0  0  0  _  _  _ _ L1 C2 LNK   _   _ _ _ exists_with_different_flags
 account A2  0  0  0  0  _  _  _ _ L1 C1   _   _   _ _ _ linked_event_failed
 account A2  0  0  0  0  _  _  _ _ L1 C1   _   _   _ _ _ ok
 account A3  0  0  0  0  _  _  _ _ L1 C1 LNK   _   _ _ _ linked_event_failed
 account A1  0  0  0  0  _  _  _ _ L2 C1   _   _   _ _ _ exists_with_different_ledger
 account A3  0  0  0  0  _  _  _ _ L1 C1 LNK   _   _ _ _ ok
 account A4  0  0  0  0  _  _  _ _ L1 C1   _   _   _ _ _ ok
 commit create_accounts

 lookup_account A7 0 0 0 0
 lookup_account A1 0 0 0 0
 lookup_account A2 0 0 0 0
 lookup_account A3 0 0 0 0
 lookup_account A4 0 0 0 0
 commit lookup_accounts
"""

T_LINKED_ACCOUNTS_2 = """
 account A7  0  0  0  0  _  _  _ _ L1 C1   _   _   _ _ _ ok
 account A1  0  0  0  0  _  _  _ _ L1 C1 LNK   _   _ _ _ linked_event_failed
 account A2  0  0  0  0  _  _  _ _ L1 C1 LNK   _   _ _ _ linked_event_failed
 account A1  0  0  0  0  _  _  _ _ L1 C1 LNK   _   _ _ _ exists
 account A3  0  0  0  0  _  _  _ _ L1 C1   _   _   _ _ _ linked_event_failed
 commit create_accounts

 lookup_account A7 0 0 0 0
 lookup_account A1 _
 lookup_account A2 _
 lookup_account A3 _
 commit lookup_accounts
"""

# reference :1629, :1650, :1668 (chain-open cases)
T_CHAIN_OPEN = """
 account A1  0  0  0  0  _  _  _ _ L1 C1 LNK   _   _ _ _ ok
 account A2  0  0  0  0  _  _  _ _ L1 C1 LNK   _   _ _ _ ok
 account A3  0  0  0  0  _  _  _ _ L1 C1   _   _   _ _ _ ok
 account A4  0  0  0  0  _  _  _ _ L1 C1 LNK   _   _ _ _ linked_event_failed
 account A5  0  0  0  0  _  _  _ _ L1 C1 LNK   _   _ _ _ linked_event_chain_open
 commit create_accounts

 lookup_account A1 0 0 0 0
 lookup_account A2 0 0 0 0
 lookup_account A3 0 0 0 0
 lookup_account A4 _
 lookup_account A5 _
 commit lookup_accounts
"""

T_CHAIN_OPEN_FAILED = """
 account A1  0  0  0  0  _  _  _ _ L1 C1   _   _   _ _ _ ok
 account A2  0  0  0  0  _  _  _ _ L1 C1 LNK   _   _ _ _ linked_event_failed
 account A1  0  0  0  0  _  _  _ _ L1 C1 LNK   _   _ _ _ exists_with_different_flags
 account A3  0  0  0  0  _  _  _ _ L1 C1 LNK   _   _ _ _ linked_event_chain_open
 commit create_accounts

 lookup_account A1 0 0 0 0
 lookup_account A2 _
 lookup_account A3 _
 commit lookup_accounts
"""

T_CHAIN_OPEN_BATCH_OF_1 = """
 account A1  0  0  0  0  _  _  _ _ L1 C1 LNK   _   _ _ _ linked_event_chain_open
 commit create_accounts

 lookup_account A1 _
 commit lookup_accounts
"""

# reference :1682 "create_transfers/lookup_transfers" — every result code in
# definition order
T_CREATE_TRANSFERS = """
 account A1  0  0  0  0  _  _  _ _ L1 C1   _   _   _ _ _ ok
 account A2  0  0  0  0  _  _  _ _ L2 C2   _   _   _ _ _ ok
 account A3  0  0  0  0  _  _  _ _ L1 C1   _   _   _ _ _ ok
 account A4  0  0  0  0  _  _  _ _ L1 C1   _ D<C   _ _ _ ok
 account A5  0  0  0  0  _  _  _ _ L1 C1   _   _ C<D _ _ ok
 commit create_accounts

 setup A1  100   200    0     0
 setup A2    0     0    0     0
 setup A3    0     0  110   210
 setup A4   20  -700    0  -500
 setup A5    0 -1000   10 -1100

 tick -3000000000

 transfer   T0 A0 A0    0  T1  _  _  _    _ L0 C0   _ PEN   _   _   _   _ P1 1 timestamp_must_be_zero
 transfer   T0 A0 A0    0  T1  _  _  _    _ L0 C0   _ PEN   _   _   _   _ P1 _ reserved_flag
 transfer   T0 A0 A0    0  T1  _  _  _    _ L0 C0   _ PEN   _   _   _   _  _ _ id_must_not_be_zero
 transfer   -0 A0 A0    0  T1  _  _  _    _ L0 C0   _ PEN   _   _   _   _  _ _ id_must_not_be_int_max
 transfer   T1 A0 A0    0  T1  _  _  _    _ L0 C0   _ PEN   _   _   _   _  _ _ debit_account_id_must_not_be_zero
 transfer   T1 -0 A0    0  T1  _  _  _    _ L0 C0   _ PEN   _   _   _   _  _ _ debit_account_id_must_not_be_int_max
 transfer   T1 A8 A0    0  T1  _  _  _    _ L0 C0   _ PEN   _   _   _   _  _ _ credit_account_id_must_not_be_zero
 transfer   T1 A8 -0    0  T1  _  _  _    _ L0 C0   _ PEN   _   _   _   _  _ _ credit_account_id_must_not_be_int_max
 transfer   T1 A8 A8    0  T1  _  _  _    _ L0 C0   _ PEN   _   _   _   _  _ _ accounts_must_be_different
 transfer   T1 A8 A9    0  T1  _  _  _    _ L0 C0   _ PEN   _   _   _   _  _ _ pending_id_must_be_zero
 transfer   T1 A8 A9    0   _  _  _  _    1 L0 C0   _   _   _   _   _   _  _ _ timeout_reserved_for_pending_transfer
 transfer   T1 A8 A9    0   _  _  _  _    _ L0 C0   _ PEN   _   _   _   _  _ _ amount_must_not_be_zero
 transfer   T1 A8 A9    9   _  _  _  _    _ L0 C0   _ PEN   _   _   _   _  _ _ ledger_must_not_be_zero
 transfer   T1 A8 A9    9   _  _  _  _    _ L9 C0   _ PEN   _   _   _   _  _ _ code_must_not_be_zero
 transfer   T1 A8 A9    9   _  _  _  _    _ L9 C1   _ PEN   _   _   _   _  _ _ debit_account_not_found
 transfer   T1 A1 A9    9   _  _  _  _    _ L9 C1   _ PEN   _   _   _   _  _ _ credit_account_not_found
 transfer   T1 A1 A2    1   _  _  _  _    _ L9 C1   _ PEN   _   _   _   _  _ _ accounts_must_have_the_same_ledger
 transfer   T1 A1 A3    1   _  _  _  _    _ L9 C1   _ PEN   _   _   _   _  _ _ transfer_must_have_the_same_ledger_as_accounts
 transfer   T1 A1 A3  -99   _  _  _  _    _ L1 C1   _ PEN   _   _   _   _  _ _ overflows_debits_pending
 transfer   T1 A1 A3 -109   _  _  _  _    _ L1 C1   _ PEN   _   _   _   _  _ _ overflows_credits_pending
 transfer   T1 A1 A3 -199   _  _  _  _    _ L1 C1   _ PEN   _   _   _   _  _ _ overflows_debits_posted
 transfer   T1 A1 A3 -209   _  _  _  _    _ L1 C1   _ PEN   _   _   _   _  _ _ overflows_credits_posted
 transfer   T1 A1 A3 -299   _  _  _  _    _ L1 C1   _ PEN   _   _   _   _  _ _ overflows_debits
 transfer   T1 A1 A3 -319   _  _  _  _    _ L1 C1   _ PEN   _   _   _   _  _ _ overflows_credits
 transfer   T1 A4 A5  199   _  _  _  _  999 L1 C1   _ PEN   _   _   _   _  _ _ overflows_timeout
 transfer   T1 A4 A5  199   _  _  _  _    _ L1 C1   _   _   _   _   _   _  _ _ exceeds_credits
 transfer   T1 A4 A5   91   _  _  _  _    _ L1 C1   _   _   _   _   _   _  _ _ exceeds_debits
 transfer   T1 A1 A3  123   _  _  _  _    1 L1 C1   _ PEN   _   _   _   _  _ _ ok
 transfer   T1 A1 A3  123   _  _  _  _    1 L2 C1   _ PEN   _   _   _   _  _ _ transfer_must_have_the_same_ledger_as_accounts
 transfer   T1 A1 A3   -0   _ U1 U1 U1    _ L1 C2   _   _   _   _   _   _  _ _ exists_with_different_flags
 transfer   T1 A3 A1   -0   _ U1 U1 U1    1 L1 C2   _ PEN   _   _   _   _  _ _ exists_with_different_debit_account_id
 transfer   T1 A1 A4   -0   _ U1 U1 U1    1 L1 C2   _ PEN   _   _   _   _  _ _ exists_with_different_credit_account_id
 transfer   T1 A1 A3   -0   _ U1 U1 U1    1 L1 C1   _ PEN   _   _   _   _  _ _ exists_with_different_amount
 transfer   T1 A1 A3  123   _ U1 U1 U1    1 L1 C2   _ PEN   _   _   _   _  _ _ exists_with_different_user_data_128
 transfer   T1 A1 A3  123   _  _ U1 U1    1 L1 C2   _ PEN   _   _   _   _  _ _ exists_with_different_user_data_64
 transfer   T1 A1 A3  123   _  _  _ U1    1 L1 C2   _ PEN   _   _   _   _  _ _ exists_with_different_user_data_32
 transfer   T1 A1 A3  123   _  _  _  _    2 L1 C2   _ PEN   _   _   _   _  _ _ exists_with_different_timeout
 transfer   T1 A1 A3  123   _  _  _  _    1 L1 C2   _ PEN   _   _   _   _  _ _ exists_with_different_code
 transfer   T1 A1 A3  123   _  _  _  _    1 L1 C1   _ PEN   _   _   _   _  _ _ exists
 transfer   T2 A3 A1    7   _  _  _  _    _ L1 C2   _   _   _   _   _   _  _ _ ok
 transfer   T3 A1 A3    3   _  _  _  _    _ L1 C2   _   _   _   _   _   _  _ _ ok
 commit create_transfers

 lookup_account A1 223 203   0   7
 lookup_account A3   0   7 233 213
 commit lookup_accounts

 lookup_transfer T1 exists true
 lookup_transfer T2 exists true
 lookup_transfer T3 exists true
 lookup_transfer -0 exists false
 commit lookup_transfers
"""

# reference :1759 "create/lookup 2-phase transfers"
T_TWO_PHASE = """
 account A1  0  0  0  0  _  _  _ _ L1 C1   _   _   _ _ _ ok
 account A2  0  0  0  0  _  _  _ _ L1 C1   _   _   _ _ _ ok
 commit create_accounts

 transfer   T1 A1 A2   15   _  _  _  _    _ L1 C1   _   _   _   _   _   _  _ _ ok
 transfer   T2 A1 A2   15   _  _  _  _ 1000 L1 C1   _ PEN   _   _   _   _  _ _ ok
 transfer   T3 A1 A2   15   _  _  _  _   50 L1 C1   _ PEN   _   _   _   _  _ _ ok
 transfer   T4 A1 A2   15   _  _  _  _    1 L1 C1   _ PEN   _   _   _   _  _ _ ok
 transfer   T5 A1 A2    7   _ U9 U9 U9   50 L1 C1   _ PEN   _   _   _   _  _ _ ok
 transfer   T6 A1 A2    1   _  _  _  _    0 L1 C1   _ PEN   _   _   _   _  _ _ ok
 commit create_transfers

 lookup_account A1 53 15  0  0
 lookup_account A2  0  0 53 15
 commit lookup_accounts

 tick 1000000000

 transfer T101 A1 A2   13  T2 U1 U1 U1    _ L1 C1   _   _ POS   _   _   _  _ _ ok
 transfer   T0 A8 A9   16  T0 U2 U2 U2   50 L6 C7   _ PEN POS VOI   _   _  _ 1 timestamp_must_be_zero
 transfer   T0 A8 A9   16  T0 U2 U2 U2   50 L6 C7   _ PEN POS VOI   _   _  _ _ id_must_not_be_zero
 transfer   -0 A8 A9   16  T0 U2 U2 U2   50 L6 C7   _ PEN POS VOI   _   _  _ _ id_must_not_be_int_max
 transfer T101 A8 A9   16  T0 U2 U2 U2   50 L6 C7   _ PEN POS VOI   _   _  _ _ flags_are_mutually_exclusive
 transfer T101 A8 A9   16  T0 U2 U2 U2   50 L6 C7   _ PEN POS VOI BDR   _  _ _ flags_are_mutually_exclusive
 transfer T101 A8 A9   16  T0 U2 U2 U2   50 L6 C7   _ PEN POS VOI BDR BCR  _ _ flags_are_mutually_exclusive
 transfer T101 A8 A9   16  T0 U2 U2 U2   50 L6 C7   _ PEN POS VOI   _ BCR  _ _ flags_are_mutually_exclusive
 transfer T101 A8 A9   16  T0 U2 U2 U2   50 L6 C7   _ PEN   _ VOI   _   _  _ _ flags_are_mutually_exclusive
 transfer T101 A8 A9   16  T0 U2 U2 U2   50 L6 C7   _   _   _ VOI BDR   _  _ _ flags_are_mutually_exclusive
 transfer T101 A8 A9   16  T0 U2 U2 U2   50 L6 C7   _   _   _ VOI BDR BCR  _ _ flags_are_mutually_exclusive
 transfer T101 A8 A9   16  T0 U2 U2 U2   50 L6 C7   _   _   _ VOI   _ BCR  _ _ flags_are_mutually_exclusive
 transfer T101 A8 A9   16  T0 U2 U2 U2   50 L6 C7   _   _ POS   _ BDR   _  _ _ flags_are_mutually_exclusive
 transfer T101 A8 A9   16  T0 U2 U2 U2   50 L6 C7   _   _ POS   _ BDR BCR  _ _ flags_are_mutually_exclusive
 transfer T101 A8 A9   16  T0 U2 U2 U2   50 L6 C7   _   _ POS   _   _ BCR  _ _ flags_are_mutually_exclusive
 transfer T101 A8 A9   16  T0 U2 U2 U2   50 L6 C7   _   _   _ VOI   _   _  _ _ pending_id_must_not_be_zero
 transfer T101 A8 A9   16  -0 U2 U2 U2   50 L6 C7   _   _   _ VOI   _   _  _ _ pending_id_must_not_be_int_max
 transfer T101 A8 A9   16 101 U2 U2 U2   50 L6 C7   _   _   _ VOI   _   _  _ _ pending_id_must_be_different
 transfer T101 A8 A9   16 102 U2 U2 U2   50 L6 C7   _   _   _ VOI   _   _  _ _ timeout_reserved_for_pending_transfer
 transfer T101 A8 A9   16 102 U2 U2 U2    _ L6 C7   _   _   _ VOI   _   _  _ _ pending_transfer_not_found
 transfer T101 A8 A9   16  T1 U2 U2 U2    _ L6 C7   _   _   _ VOI   _   _  _ _ pending_transfer_not_pending
 transfer T101 A8 A9   16  T3 U2 U2 U2    _ L6 C7   _   _   _ VOI   _   _  _ _ pending_transfer_has_different_debit_account_id
 transfer T101 A1 A9   16  T3 U2 U2 U2    _ L6 C7   _   _   _ VOI   _   _  _ _ pending_transfer_has_different_credit_account_id
 transfer T101 A1 A2   16  T3 U2 U2 U2    _ L6 C7   _   _   _ VOI   _   _  _ _ pending_transfer_has_different_ledger
 transfer T101 A1 A2   16  T3 U2 U2 U2    _ L1 C7   _   _   _ VOI   _   _  _ _ pending_transfer_has_different_code
 transfer T101 A1 A2   16  T3 U2 U2 U2    _ L1 C1   _   _   _ VOI   _   _  _ _ exceeds_pending_transfer_amount
 transfer T101 A1 A2   14  T3 U2 U2 U2    _ L1 C1   _   _   _ VOI   _   _  _ _ pending_transfer_has_different_amount
 transfer T101 A1 A2   15  T3 U2 U2 U2    _ L1 C1   _   _   _ VOI   _   _  _ _ exists_with_different_flags
 transfer T101 A1 A2   14  T2 U1 U1 U1    _ L1 C1   _   _ POS   _   _   _  _ _ exists_with_different_amount
 transfer T101 A1 A2    _  T2 U1 U1 U1    _ L1 C1   _   _ POS   _   _   _  _ _ exists_with_different_amount
 transfer T101 A1 A2   13  T3 U2 U2 U2    _ L1 C1   _   _ POS   _   _   _  _ _ exists_with_different_pending_id
 transfer T101 A1 A2   13  T2 U2 U2 U2    _ L1 C1   _   _ POS   _   _   _  _ _ exists_with_different_user_data_128
 transfer T101 A1 A2   13  T2 U1 U2 U2    _ L1 C1   _   _ POS   _   _   _  _ _ exists_with_different_user_data_64
 transfer T101 A1 A2   13  T2 U1 U1 U2    _ L1 C1   _   _ POS   _   _   _  _ _ exists_with_different_user_data_32
 transfer T101 A1 A2   13  T2 U1 U1 U1    _ L1 C1   _   _ POS   _   _   _  _ _ exists
 transfer T102 A1 A2   13  T2 U1 U1 U1    _ L1 C1   _   _ POS   _   _   _  _ _ pending_transfer_already_posted
 transfer T103 A1 A2   15  T3 U1 U1 U1    _ L1 C1   _   _   _ VOI   _   _  _ _ ok
 transfer T102 A1 A2   13  T3 U1 U1 U1    _ L1 C1   _   _ POS   _   _   _  _ _ pending_transfer_already_voided
 transfer T102 A1 A2   15  T4 U1 U1 U1    _ L1 C1   _   _   _ VOI   _   _  _ _ pending_transfer_expired
 transfer T105 A0 A0    _  T5 U0 U0 U0    _ L0 C0   _   _ POS   _   _   _  _ _ ok
 transfer T106 A0 A0    0  T6 U0 U0 U0    _ L1 C1   _   _ POS   _   _   _  _ _ ok
 commit create_transfers

 lookup_account A1 15 36  0  0
 lookup_account A2  0  0 15 36
 commit lookup_accounts
"""

# reference :1839 / :1859 / :1885
T_FAILED_NOT_EXIST = """
 account A1  0  0  0  0  _  _  _ _ L1 C1   _   _   _ _ _ ok
 account A2  0  0  0  0  _  _  _ _ L1 C1   _   _   _ _ _ ok
 commit create_accounts

 transfer   T1 A1 A2   15   _  _  _  _    _ L1 C1   _   _   _   _   _   _  _ _ ok
 transfer   T2 A1 A2   15   _  _  _  _    _ L0 C1   _   _   _   _   _   _  _ _ ledger_must_not_be_zero
 commit create_transfers

 lookup_account A1 0 15 0  0
 lookup_account A2 0  0 0 15
 commit lookup_accounts

 lookup_transfer T1 exists true
 lookup_transfer T2 exists false
 commit lookup_transfers
"""

T_LINKED_CHAINS_UNDONE = """
 account A1  0  0  0  0  _  _  _ _ L1 C1   _   _   _ _ _ ok
 account A2  0  0  0  0  _  _  _ _ L1 C1   _   _   _ _ _ ok
 commit create_accounts

 transfer   T1 A1 A2   15   _  _  _  _    _ L1 C1 LNK   _   _   _   _   _  _ _ linked_event_failed
 transfer   T2 A1 A2   15   _  _  _  _    _ L0 C1   _   _   _   _   _   _  _ _ ledger_must_not_be_zero
 commit create_transfers

 transfer   T3 A1 A2   15   _  _  _  _    1 L1 C1 LNK PEN   _   _   _   _  _ _ linked_event_failed
 transfer   T4 A1 A2   15   _  _  _  _    _ L0 C1   _   _   _   _   _   _  _ _ ledger_must_not_be_zero
 commit create_transfers

 lookup_account A1 0 0 0 0
 lookup_account A2 0 0 0 0
 commit lookup_accounts

 lookup_transfer T1 exists false
 lookup_transfer T2 exists false
 lookup_transfer T3 exists false
 lookup_transfer T4 exists false
 commit lookup_transfers
"""

T_LINKED_CHAINS_UNDONE_WITHIN = """
 account A1  0  0  0  0  _  _  _ _ L1 C1   _ D<C   _ _ _ ok
 account A2  0  0  0  0  _  _  _ _ L1 C1   _   _   _ _ _ ok
 commit create_accounts

 setup A1 0 0 0 20

 transfer   T1 A1 A2   15   _ _   _  _    _ L1 C1 LNK   _   _   _   _   _  _ _ linked_event_failed
 transfer   T2 A1 A2    5   _ _   _  _    _ L0 C1   _   _   _   _   _   _  _ _ ledger_must_not_be_zero
 transfer   T3 A1 A2   15   _ _   _  _    _ L1 C1   _   _   _   _   _   _  _ _ ok
 commit create_transfers

 lookup_account A1 0 15 0 20
 lookup_account A2 0  0 0 15
 commit lookup_accounts

 lookup_transfer T1 exists false
 lookup_transfer T2 exists false
 lookup_transfer T3 exists true
 commit lookup_transfers
"""

# reference :1909 / :1953 / :1985 / :2015 / :2046 (balancing)
T_BALANCING_LIMIT = """
 account A1  0  0  0  0  _  _  _ _ L1 C1   _ D<C   _ _ _ ok
 account A2  0  0  0  0  _  _  _ _ L1 C1   _   _ C<D _ _ ok
 account A3  0  0  0  0  _  _  _ _ L1 C1   _   _   _ _ _ ok
 commit create_accounts

 setup A1 1  0 0 10
 setup A2 0 10 2  0

 transfer   T1 A1 A3  3     _  _  _  _    _ L2 C1   _   _   _   _ BDR   _  _ _ transfer_must_have_the_same_ledger_as_accounts
 transfer   T1 A3 A2  3     _  _  _  _    _ L2 C1   _   _   _   _   _ BCR  _ _ transfer_must_have_the_same_ledger_as_accounts
 transfer   T1 A1 A3  3     _  _  _  _    _ L1 C1   _   _   _   _ BDR   _  _ _ ok
 transfer   T2 A1 A3 13     _  _  _  _    _ L1 C1   _   _   _   _ BDR   _  _ _ ok
 transfer   T3 A3 A2  3     _  _  _  _    _ L1 C1   _   _   _   _   _ BCR  _ _ ok
 transfer   T4 A3 A2 13     _  _  _  _    _ L1 C1   _   _   _   _   _ BCR  _ _ ok
 transfer   T5 A1 A3  1     _  _  _  _    _ L1 C1   _   _   _   _ BDR   _  _ _ exceeds_credits
 transfer   T5 A1 A3  1     _  _  _  _    _ L1 C1   _   _   _   _ BDR BCR  _ _ exceeds_credits
 transfer   T5 A3 A2  1     _  _  _  _    _ L1 C1   _   _   _   _   _ BCR  _ _ exceeds_debits
 transfer   T5 A1 A2  1     _  _  _  _    _ L1 C1   _   _   _   _ BDR BCR  _ _ exceeds_credits
 transfer   T1 A1 A3    2   _  _  _  _    _ L1 C1   _   _   _   _ BDR   _  _ _ exists_with_different_amount
 transfer   T1 A1 A3    4   _  _  _  _    _ L1 C1   _   _   _   _ BDR   _  _ _ exists_with_different_amount
 transfer   T1 A1 A3    3   _  _  _  _    _ L1 C1   _   _   _   _ BDR   _  _ _ exists
 transfer   T2 A1 A3    6   _  _  _  _    _ L1 C1   _   _   _   _ BDR   _  _ _ exists
 transfer   T3 A3 A2    3   _  _  _  _    _ L1 C1   _   _   _   _   _ BCR  _ _ exists
 transfer   T4 A3 A2    5   _  _  _  _    _ L1 C1   _   _   _   _   _ BCR  _ _ exists
 commit create_transfers

 lookup_account A1 1  9 0 10
 lookup_account A2 0 10 2  8
 lookup_account A3 0  8 0  9
 commit lookup_accounts

 lookup_transfer T1 amount 3
 lookup_transfer T2 amount 6
 lookup_transfer T3 amount 3
 lookup_transfer T4 amount 5
 lookup_transfer T5 exists false
 commit lookup_transfers
"""

T_BALANCING_NO_LIMIT = """
 account A1  0  0  0  0  _  _  _ _ L1 C1   _   _   _ _ _ ok
 account A2  0  0  0  0  _  _  _ _ L1 C1   _   _   _ _ _ ok
 account A3  0  0  0  0  _  _  _ _ L1 C1   _   _   _ _ _ ok
 commit create_accounts

 setup A1 1  0 0 10
 setup A2 0 10 2  0

 transfer   T1 A3 A1   99   _  _  _  _    _ L1 C1   _   _   _   _ BDR BCR  _ _ exceeds_credits
 transfer   T1 A3 A1   99   _  _  _  _    _ L1 C1   _   _   _   _ BDR   _  _ _ exceeds_credits
 transfer   T1 A2 A3   99   _  _  _  _    _ L1 C1   _   _   _   _   _ BCR  _ _ exceeds_debits
 transfer   T1 A1 A3   99   _  _  _  _    _ L1 C1   _   _   _   _ BDR   _  _ _ ok
 transfer   T2 A1 A3   99   _  _  _  _    _ L1 C1   _   _   _   _ BDR   _  _ _ exceeds_credits
 transfer   T3 A3 A2   99   _  _  _  _    _ L1 C1   _   _   _   _   _ BCR  _ _ ok
 transfer   T4 A3 A2   99   _  _  _  _    _ L1 C1   _   _   _   _   _ BCR  _ _ exceeds_debits
 commit create_transfers

 lookup_account A1 1  9 0 10
 lookup_account A2 0 10 2  8
 lookup_account A3 0  8 0  9
 commit lookup_accounts

 lookup_transfer T1 amount 9
 lookup_transfer T2 exists false
 lookup_transfer T3 amount 8
 lookup_transfer T4 exists false
 commit lookup_transfers
"""

T_BALANCING_AMOUNT_0 = """
 account A1  0  0  0  0  _  _  _ _ L1 C1   _ D<C   _ _ _ ok
 account A2  0  0  0  0  _  _  _ _ L1 C1   _   _ C<D _ _ ok
 account A3  0  0  0  0  _  _  _ _ L1 C1   _   _ C<D _ _ ok
 account A4  0  0  0  0  _  _  _ _ L1 C1   _   _   _ _ _ ok
 commit create_accounts

 setup A1 1  0 0 10
 setup A2 0 10 2  0
 setup A3 0 10 2  0

 transfer   T1 A1 A4    0   _  _  _  _    _ L1 C1   _   _   _   _ BDR   _  _ _ ok
 transfer   T2 A4 A2    0   _  _  _  _    _ L1 C1   _   _   _   _   _ BCR  _ _ ok
 transfer   T3 A4 A3    0   _  _  _  _    _ L1 C1   _ PEN   _   _   _ BCR  _ _ ok
 commit create_transfers

 lookup_account A1 1  9  0 10
 lookup_account A2 0 10  2  8
 lookup_account A3 0 10 10  0
 lookup_account A4 8  8  0  9
 commit lookup_accounts

 lookup_transfer T1 amount 9
 lookup_transfer T2 amount 8
 lookup_transfer T3 amount 8
 commit lookup_transfers
"""

T_BALANCING_BOTH = """
 account A1  0  0  0  0  _  _  _ _ L1 C1   _ D<C   _ _ _ ok
 account A2  0  0  0  0  _  _  _ _ L1 C1   _   _ C<D _ _ ok
 account A3  0  0  0  0  _  _  _ _ L1 C1   _   _   _ _ _ ok
 commit create_accounts

 setup A1 0  0 0 20
 setup A2 0 10 0  0
 setup A3 0 99 0  0

 transfer   T1 A1 A2    1   _  _  _  _    _ L1 C1   _   _   _   _ BDR BCR  _ _ ok
 transfer   T2 A1 A2   12   _  _  _  _    _ L1 C1   _   _   _   _ BDR BCR  _ _ ok
 transfer   T3 A1 A2    1   _  _  _  _    _ L1 C1   _   _   _   _ BDR BCR  _ _ exceeds_debits
 transfer   T3 A1 A3   12   _  _  _  _    _ L1 C1   _   _   _   _ BDR BCR  _ _ ok
 transfer   T4 A1 A3    1   _  _  _  _    _ L1 C1   _   _   _   _ BDR BCR  _ _ exceeds_credits
 commit create_transfers

 lookup_account A1 0 20 0 20
 lookup_account A2 0 10 0 10
 lookup_account A3 0 99 0 10
 commit lookup_accounts

 lookup_transfer T1 amount  1
 lookup_transfer T2 amount  9
 lookup_transfer T3 amount 10
 lookup_transfer T4 exists false
 commit lookup_transfers
"""

T_BALANCING_PENDING = """
 account A1  0  0  0  0  _  _  _ _ L1 C1   _ D<C   _ _ _ ok
 account A2  0  0  0  0  _  _  _ _ L1 C1   _   _ C<D _ _ ok
 commit create_accounts

 setup A1 0  0 0 10
 setup A2 0 10 0  0

 transfer   T1 A1 A2    3   _  _  _  _    _ L1 C1   _ PEN   _   _ BDR   _  _ _ ok
 transfer   T2 A1 A2   13   _  _  _  _    _ L1 C1   _ PEN   _   _ BDR   _  _ _ ok
 transfer   T3 A1 A2    1   _  _  _  _    _ L1 C1   _ PEN   _   _ BDR   _  _ _ exceeds_credits
 commit create_transfers

 lookup_account A1 10  0  0 10
 lookup_account A2  0 10 10  0
 commit lookup_accounts

 transfer   T3 A1 A2    0  T1  _  _  _    _ L1 C1   _   _ POS   _   _   _  _ _ ok
 transfer   T4 A1 A2    5  T2  _  _  _    _ L1 C1   _   _ POS   _   _   _  _ _ ok
 commit create_transfers

 lookup_transfer T1 amount  3
 lookup_transfer T2 amount  7
 lookup_transfer T3 amount  3
 lookup_transfer T4 amount  5
 commit lookup_transfers
"""

ORACLE_TABLES = {
    "create_accounts": T_CREATE_ACCOUNTS,
    "linked_accounts_1": T_LINKED_ACCOUNTS_1,
    "linked_accounts_2": T_LINKED_ACCOUNTS_2,
    "chain_open": T_CHAIN_OPEN,
    "chain_open_failed": T_CHAIN_OPEN_FAILED,
    "chain_open_batch_of_1": T_CHAIN_OPEN_BATCH_OF_1,
    "create_transfers": T_CREATE_TRANSFERS,
    "two_phase": T_TWO_PHASE,
    "failed_not_exist": T_FAILED_NOT_EXIST,
    "linked_chains_undone": T_LINKED_CHAINS_UNDONE,
    "linked_chains_undone_within": T_LINKED_CHAINS_UNDONE_WITHIN,
    "balancing_limit": T_BALANCING_LIMIT,
    "balancing_no_limit": T_BALANCING_NO_LIMIT,
    "balancing_amount_0": T_BALANCING_AMOUNT_0,
    "balancing_both": T_BALANCING_BOTH,
    "balancing_pending": T_BALANCING_PENDING,
}

# tables without raw-balance `setup`: runnable against the device kernels too
DEVICE_TABLES = [
    "create_accounts", "linked_accounts_1", "linked_accounts_2",
    "chain_open", "chain_open_failed", "chain_open_batch_of_1",
    "failed_not_exist", "linked_chains_undone", "two_phase",
]


@pytest.mark.parametrize("name", sorted(ORACLE_TABLES))
def test_golden_oracle(name):
    run_table(ORACLE_TABLES[name])


@pytest.mark.parametrize("name", DEVICE_TABLES)
def test_golden_device(name):
    run_table(ORACLE_TABLES[name], device=True)


@pytest.mark.parametrize("name", DEVICE_TABLES)
def test_golden_native(name):
    """The native C++ engine replays the reference's own test tables with
    bit-exact result codes (native/ledger.cc parity contract)."""
    from tigerbeetle_tpu.models.native_ledger import NativeLedger

    run_table(ORACLE_TABLES[name], backend=lambda: NativeLedger(10, 10))
