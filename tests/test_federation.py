"""Cross-ledger federation (federation/): commitment-chain determinism,
the external stream verifier's tamper rejection (naming the exact
divergent checkpoint), the sans-IO settlement agent's state machine, and
the seed-deterministic two-region composite scenario.

The composite runs once per module (fixture) and feeds three tests: the
determinism check re-runs the same seed and compares result dicts
byte-for-byte; the tamper tests replay the captured region-0 CDC stream
through `inspect commitments --stream` pristine (accepted, head matches
the replica's published chain) and edited (rejected at the first
checkpoint covering the edit)."""

import json

import pytest

import tests.conftest  # noqa: F401 — CPU platform before jax init
from tigerbeetle_tpu.federation.agent import SettlementCore
from tigerbeetle_tpu.federation.commitment import (
    FP_FIELDS,
    CommitmentLog,
    CommitmentMismatch,
    fold_commitment,
)
from tigerbeetle_tpu.federation.topology import (
    FEDERATION_LEDGER,
    SETTLE_CODE,
    FederationTopology,
    escrow_account_id,
    home_account_id,
    mirror_account_id,
    origin_id,
    settlement_id,
)
from tigerbeetle_tpu.types import TransferFlags

SEED = 12  # drawn into the vopr federation slice too (12 * PRIME32_3)


def _fp(base: int) -> dict:
    """A synthetic five-field fingerprint (values just need to differ)."""
    return {k: base + i for i, k in enumerate(FP_FIELDS)}


# -- the chain fold + CommitmentLog --------------------------------------


def test_fold_commitment_deterministic_and_sensitive():
    a = fold_commitment(0, 10, _fp(100))
    assert a == fold_commitment(0, 10, _fp(100))  # pure
    assert a != fold_commitment(0, 10, _fp(101))  # fp-sensitive
    assert a != fold_commitment(0, 20, _fp(100))  # op-sensitive
    assert a != fold_commitment(1, 10, _fp(100))  # chain-sensitive
    # extra keys are ignored: only FP_FIELDS participate
    fat = dict(_fp(100), posted=999, extra=1)
    assert fold_commitment(0, 10, fat) == a


def test_commitment_log_chain_idempotent_and_tamper():
    log = CommitmentLog(interval=10)
    c10 = log.record(10, _fp(1))
    c20 = log.record(20, _fp(2))
    assert log.head_op == 20 and log.head == c20 and c10 != c20
    # idempotent re-record (WAL-tail replay): same op, same fp, same value
    assert log.record(10, _fp(1)) == c10
    # a tampered re-record names the checkpoint
    with pytest.raises(CommitmentMismatch) as e:
        log.record(10, _fp(3))
    assert e.value.op == 10
    # boundaries must stay contiguous — a skipped checkpoint is a fault
    with pytest.raises(CommitmentMismatch) as e:
        log.record(40, _fp(4))
    assert e.value.op == 40


def test_commitment_log_snapshot_restore_roundtrip():
    log = CommitmentLog(interval=5)
    for i in range(1, 7):
        log.record(5 * i, _fp(i))
    fresh = CommitmentLog(interval=5)
    fresh.restore(json.loads(json.dumps(log.snapshot())))  # JSON-safe
    assert (fresh.head_op, fresh.head) == (log.head_op, log.head)
    assert fresh.ops() == log.ops()
    assert fresh.get(15) == log.get(15)
    # both continue identically from the restored head
    assert fresh.record(35, _fp(7)) == log.record(35, _fp(7))


def test_commitment_log_ring_trims_but_keeps_head():
    log = CommitmentLog(interval=1, ring=4)
    for op in range(1, 11):
        log.record(op, _fp(op))
    assert len(log.ops()) == 4 and log.ops() == [7, 8, 9, 10]
    assert log.head_op == 10
    assert log.get(1) is None
    # older than the ring: blind-accept (no evidence either way)
    assert log.record(1, _fp(999)) is None


def test_commitment_log_first_divergence():
    a, b = CommitmentLog(interval=10), CommitmentLog(interval=10)
    for op in (10, 20):
        a.record(op, _fp(op))
        b.record(op, _fp(op))
    a.record(30, _fp(30))
    b.record(30, _fp(31))  # state diverged in the third interval
    a.record(40, _fp(40))
    b.record(40, _fp(40))  # same input, but the chain stays poisoned
    assert a.first_divergence(b) == 30


# -- chain portability across backends -----------------------------------


def test_commitment_chain_backend_parity_native_vs_oracle():
    """The chain is a pure function of committed history: the native C++
    engine and the numpy oracle, driven with the SAME batches and
    timestamps, fold bit-identical commitment chains at every boundary
    (the external-verifier trust model depends on exactly this)."""
    from tigerbeetle_tpu.models.native_ledger import NativeLedger
    from tigerbeetle_tpu.models.oracle import OracleStateMachine
    from tigerbeetle_tpu.testing.workload import WorkloadGenerator

    gen = WorkloadGenerator(SEED)
    nat = NativeLedger(12, 14)
    ora = OracleStateMachine()
    chain_nat = chain_ora = 0
    for b in range(8):
        if b % 3 == 0:
            op, events = gen.gen_accounts_batch(16)
        else:
            op, events = gen.gen_transfers_batch(16)
        nat.prepare(op, len(events))
        ts = nat.prepare_timestamp
        codes_nat = nat.execute_dense(op, ts, list(events))
        codes_ora = ora.execute_dense(op, ts, list(events))
        assert [int(c) for c in codes_nat] == [int(c) for c in codes_ora]
        if b % 2 == 1:  # a checkpoint boundary every second batch
            chain_nat = fold_commitment(chain_nat, b + 1, nat.fingerprint())
            chain_ora = fold_commitment(chain_ora, b + 1, ora.fingerprint())
            assert chain_nat == chain_ora, f"chains diverged at batch {b}"
    assert chain_nat != 0


# -- the settlement agent's sans-IO core ---------------------------------


def _outbound_line(op: int, ix: int, seq: int, amount: int,
                   beneficiary: int, src: int = 0, dst: int = 1) -> str:
    """A committed origin-pending CDC record leaving region `src`."""
    return json.dumps({
        "kind": "transfer", "op": op, "ix": ix, "ts": 1000 + op,
        "result": 0, "id": origin_id(src, seq),
        "debit_account_id": home_account_id(src, 0, 2),
        "credit_account_id": escrow_account_id(src, dst),
        "amount": amount, "ledger": FEDERATION_LEDGER,
        "code": SETTLE_CODE, "flags": int(TransferFlags.pending),
        "user_data_128": beneficiary,
    })


def test_settlement_core_happy_path_posts_both_legs():
    topo = FederationTopology.of(2)
    core = SettlementCore(topo, region=0)
    assert core.emit_lines([_outbound_line(3, 0, seq=1, amount=50,
                                           beneficiary=77)])
    assert core.dsts_with_work() == {1}
    legs = core.next_mirror_batch(1)
    [t] = core.mirror_transfers(legs)
    assert t.id == settlement_id(0, 3, 0, 0)
    assert t.debit_account_id == mirror_account_id(1, 0)
    assert t.credit_account_id == 77 and t.amount == 50
    assert t.user_data_128 == origin_id(0, 1) and t.user_data_64 == 3
    core.on_mirror_replies(legs, [0])
    legs = core.next_resolve_batch()
    [r] = core.resolve_transfers(legs)
    assert r.id == settlement_id(0, 3, 0, 1)
    assert r.pending_id == origin_id(0, 1) and r.amount == 0
    assert r.flags == int(TransferFlags.post_pending_transfer)
    core.on_resolve_replies(legs, [0])
    assert core.idle() and core.stats["legs_posted"] == 1
    assert core.watermark() == 3


def test_settlement_core_mirror_rejection_voids_origin():
    topo = FederationTopology.of(2)
    core = SettlementCore(topo, region=0)
    core.emit_lines([_outbound_line(4, 0, seq=2, amount=9,
                                    beneficiary=0xBAD)])
    legs = core.next_mirror_batch(1)
    core.on_mirror_replies(legs, [3])  # terminal rejection on dst
    legs = core.next_resolve_batch()
    [r] = core.resolve_transfers(legs)
    assert r.flags == int(TransferFlags.void_pending_transfer)
    core.on_resolve_replies(legs, [0])
    assert core.stats["legs_voided"] == 1 and core.stats["legs_posted"] == 0


def test_settlement_core_dedup_window_and_gap():
    topo = FederationTopology.of(2)
    core = SettlementCore(topo, region=0, window=1)
    line = _outbound_line(5, 0, seq=3, amount=7, beneficiary=77)
    assert core.emit_lines([line])
    # redelivery of an already-staged op is dropped, not double-staged
    assert core.emit_lines([line])
    assert core.stats["redeliveries"] == 1 and core.pending_count() == 1
    # window full: the whole NEXT op is refused before staging anything
    two = [_outbound_line(6, i, seq=4 + i, amount=1, beneficiary=77)
           for i in range(2)]
    assert not core.emit_lines(two)
    assert core.stats["refusals"] == 1 and core.pending_count() == 1
    # watermark holds below the unresolved op — the durable cursor may
    # never overtake in-flight work
    assert core.watermark() == 4
    # a gap record poisons a strict core: origin history is gone
    core.emit_lines([json.dumps({"kind": "gap", "from": 7, "to": 9})])
    assert core.error is not None and "gap" in core.error


def test_settlement_core_ids_deterministic_across_lives():
    """A crashed agent's replacement re-derives the SAME settlement-leg
    ids from the redelivered stream — the remote ledger's `exists` result
    is what makes at-least-once delivery exactly-once in effect."""
    topo = FederationTopology.of(2)
    lines = [_outbound_line(8, i, seq=10 + i, amount=5, beneficiary=77)
             for i in range(3)]
    ids = []
    for _life in range(2):
        core = SettlementCore(topo, region=0)
        core.emit_lines(lines)
        legs = core.next_mirror_batch(1)
        ids.append([t.id for t in core.mirror_transfers(legs)])
    assert ids[0] == ids[1] and len(set(ids[0])) == 3


# -- the two-region composite scenario -----------------------------------


@pytest.fixture(scope="module")
def fed_run():
    from tigerbeetle_tpu.federation.sim import SimFederation

    fed = SimFederation(SEED, ticks=1200)
    return fed, fed.run()


def test_sim_federation_scenario_green(fed_run):
    _, result = fed_run
    assert result["regions"] == 2
    assert result["issued"] > 0
    assert result["settled"] + result["voided"] >= result["issued"]
    assert result["agent_crashes"] > 0  # the schedule actually fired
    assert result["region_killed"] in (0, 1)
    assert result["conservation"]["ok"]
    for region in (0, 1):
        assert result["stream_verify"][region]["checked"] > 0


def test_sim_federation_seed_deterministic(fed_run):
    """Same seed ⇒ byte-identical composite result: committed ops,
    settlement counts, the region kill, both commitment chains, and the
    verifier heads all reproduce (this is what makes a vopr federation
    seed replayable)."""
    from tigerbeetle_tpu.federation.sim import run_federation_sim

    _, first = fed_run
    assert run_federation_sim(SEED, ticks=1200) == first


def _write_stream(fed, region: int, path) -> list[int]:
    """Dump a region's captured CDC stream to JSONL; return the
    checkpoint (commitment-record) ops in order."""
    boundary_ops = []
    with open(path, "w") as f:
        for op in sorted(fed.streams[region]):
            for ln in fed.streams[region][op]:
                rec = json.loads(ln)
                if rec.get("kind") == "commitment":
                    boundary_ops.append(int(rec["op"]))
                f.write(ln.strip() + "\n")
    return boundary_ops


def test_inspect_commitments_stream_accepts_pristine(fed_run, tmp_path,
                                                     capsys):
    from tigerbeetle_tpu.cli import main

    fed, result = fed_run
    path = tmp_path / "region0.jsonl"
    assert _write_stream(fed, 0, path)
    assert main(["inspect", "commitments", "--stream", str(path),
                 "--json"]) == 0
    report = json.loads(capsys.readouterr().out)
    assert report["ok"] and report["checked"] > 0
    assert report["head_op"] == result["commitment_heads"][0][0]
    assert int(report["head"]) == result["commitment_heads"][0][1]


def test_inspect_commitments_stream_rejects_tamper(fed_run, tmp_path,
                                                   capsys):
    """Edit one committed transfer amount in the stream: the verifier
    must reject, naming the FIRST checkpoint whose commitment covers the
    edited op — not merely 'somewhere', the exact boundary."""
    from tigerbeetle_tpu.cli import main

    fed, _ = fed_run
    path = tmp_path / "region0_tampered.jsonl"
    boundary_ops = _write_stream(fed, 0, path)
    lines = path.read_text().splitlines()
    target_op = None
    for i, ln in enumerate(lines):
        rec = json.loads(ln)
        if (rec.get("kind") == "transfer" and rec.get("result") == 0
                and rec.get("amount", 0) > 0
                and rec["op"] <= boundary_ops[-1]):
            rec["amount"] = int(rec["amount"]) + 1
            lines[i] = json.dumps(rec)
            target_op = int(rec["op"])
            break
    assert target_op is not None
    path.write_text("\n".join(lines) + "\n")
    assert main(["inspect", "commitments", "--stream", str(path),
                 "--json"]) == 1
    report = json.loads(capsys.readouterr().out)
    assert not report["ok"]
    expected = min(op for op in boundary_ops if op >= target_op)
    assert report["first_divergent"] == expected
    assert str(expected) in report["error"]
