"""Conflict-wave scheduler (HazardTracker.plan + _execute_waves):
deterministic wave layout, bit-exact parity vs the scalar oracle on
adversarial hot-account workloads, and the decision plumbing.

The determinism contract under test: the wave layout is a PURE FUNCTION
of the batch bytes plus the tracker's committed-history state — no
seeds, no wall clock, no unordered iteration — so every replica and the
simulator plan (and execute) a batch identically. The parity contract:
whatever the layout, the committed result codes and the full state are
bit-exact against the oracle's strictly-serial semantics.
"""

import numpy as np
import pytest

from tigerbeetle_tpu.constants import TEST_PROCESS
from tigerbeetle_tpu.metrics import CATALOG, Metrics
from tigerbeetle_tpu.models.ledger import (
    WAVE_CAP,
    DeviceLedger,
    HazardTracker,
)
from tigerbeetle_tpu.models.oracle import OracleStateMachine
from tigerbeetle_tpu.types import (
    Account,
    AccountFlags,
    Operation,
    Transfer,
    TransferFlags,
    transfers_to_np,
)

F_PENDING = int(TransferFlags.pending)
F_POST = int(TransferFlags.post_pending_transfer)
F_VOID = int(TransferFlags.void_pending_transfer)
F_LINKED = int(TransferFlags.linked)


def _pair(n_accounts=24, limit_accounts=(), funded=200):
    """(oracle, device, ts): n accounts; `limit_accounts` get
    debits_must_not_exceed_credits and `funded` of credit headroom."""
    oracle = OracleStateMachine()
    dev = DeviceLedger(process=TEST_PROCESS, mode="auto")
    ts = 10_000
    accounts = [
        Account(
            id=i, ledger=1, code=1,
            flags=int(AccountFlags.debits_must_not_exceed_credits)
            if i in limit_accounts else 0,
        )
        for i in range(1, n_accounts + 1)
    ]
    ts += len(accounts)
    assert oracle.execute_dense(Operation.create_accounts, ts, accounts) == \
        dev.execute_dense(Operation.create_accounts, ts, accounts)
    if limit_accounts:
        fund = [
            Transfer(id=900_000 + a, debit_account_id=n_accounts,
                     credit_account_id=a, amount=funded, ledger=1, code=1)
            for a in limit_accounts
        ]
        ts += len(fund)
        assert oracle.execute_dense(Operation.create_transfers, ts, fund) == \
            dev.execute_dense(Operation.create_transfers, ts, fund)
    return oracle, dev, ts


def _check(oracle, dev, ts, transfers):
    ts += len(transfers)
    dense_o = oracle.execute_dense(Operation.create_transfers, ts, transfers)
    dense_d = dev.execute_dense(Operation.create_transfers, ts, transfers)
    assert dense_d == dense_o, [
        (i, d, o) for i, (d, o) in enumerate(zip(dense_d, dense_o)) if d != o
    ][:6]
    oracle.assert_parity(dev)
    return ts


# ----------------------------------------------------------------------
# determinism of the layout itself
# ----------------------------------------------------------------------


def _adversarial_batch():
    tr = []
    for i in range(10):  # same-batch pend->post pairs on a hot account
        tr.append(Transfer(id=1000 + i, debit_account_id=1,
                           credit_account_id=2 + i % 5, amount=10, ledger=1,
                           code=1, flags=F_PENDING))
    for i in range(10):
        tr.append(Transfer(id=2000 + i, pending_id=1000 + i, amount=5,
                           flags=F_POST))
    for _ in range(3):  # duplicate-id chain
        tr.append(Transfer(id=3000, debit_account_id=3, credit_account_id=4,
                           amount=1, ledger=1, code=1))
    for i in range(12):  # limit-account touches (order-sensitive)
        tr.append(Transfer(id=4000 + i, debit_account_id=7,
                           credit_account_id=8 + i % 4, amount=3, ledger=1,
                           code=1))
    return transfers_to_np(tr)


def _tracker(reverse_registry=False):
    t = HazardTracker()
    t.limit_account_ids = {7}
    t._limit_lo = np.array([7], dtype=np.uint64)
    pend = [(500 + i, (11 + i, 12 + i)) for i in range(6)]
    for pid, acc in (reversed(pend) if reverse_registry else pend):
        t.pending_accounts[pid] = acc
    return t


def test_wave_layout_is_a_pure_function_of_batch_and_state():
    """Same batch bytes + same tracker state => byte-identical layout,
    including with the pending registry built in a different insertion
    order (layout must not depend on dict ordering)."""
    arr = _adversarial_batch()
    d1, p1 = _tracker().plan(arr.copy())
    d2, p2 = _tracker().plan(arr.copy())
    d3, p3 = _tracker(reverse_registry=True).plan(arr.copy())
    assert d1 == d2 == d3 == "waves"
    assert p1.wave_of.tobytes() == p2.wave_of.tobytes() == p3.wave_of.tobytes()
    assert p1.n_waves == p2.n_waves == p3.n_waves
    assert p1.has_pv == p2.has_pv == p3.has_pv
    # the layout is genuinely multi-wave: posts after creators, dup ids
    # and limit touches chained
    assert p1.n_waves >= 3


def test_wave_layout_identical_across_replica_instances():
    """Two independent device ledgers fed the same committed op stream
    plan every batch identically AND produce byte-identical state — the
    cross-replica half of the determinism contract."""
    devs = [DeviceLedger(process=TEST_PROCESS, mode="auto") for _ in range(2)]
    ts = 10_000
    accounts = [Account(id=i, ledger=1, code=1) for i in range(1, 25)]
    ts += len(accounts)
    for d in devs:
        d.execute_dense(Operation.create_accounts, ts, accounts)
    rng = np.random.default_rng(9)
    for batch in range(4):
        tr = []
        base = 10_000 * (batch + 1)
        for i in range(8):
            tr.append(Transfer(id=base + i, debit_account_id=1,
                               credit_account_id=2 + i % 6, amount=4,
                               ledger=1, code=1, flags=F_PENDING))
        for i in range(8):
            tr.append(Transfer(id=base + 100 + i, pending_id=base + i,
                               flags=F_POST if i % 2 else F_VOID))
        for i in range(16):
            a = int(rng.integers(2, 24))
            tr.append(Transfer(id=base + 200 + i, debit_account_id=1,
                               credit_account_id=a, amount=1, ledger=1,
                               code=1))
        arr = transfers_to_np(tr)
        plans = []
        for d in devs:
            probe = HazardTracker()
            probe.pending_accounts = dict(d.hazards.pending_accounts)
            probe.limit_account_ids = set(d.hazards.limit_account_ids)
            probe._limit_lo = d.hazards._limit_lo.copy()
            plans.append(probe.plan(arr.copy()))
        (d1, p1), (d2, p2) = plans
        assert d1 == d2
        if p1 is not None:
            assert p1.wave_of.tobytes() == p2.wave_of.tobytes()
        ts += len(tr)
        dense = [d.execute_dense(Operation.create_transfers, ts, arr.copy())
                 for d in devs]
        assert dense[0] == dense[1]
    f1, f2 = devs[0].fingerprint(), devs[1].fingerprint()
    assert f1 == f2


# ----------------------------------------------------------------------
# parity on adversarial hot-account workloads
# ----------------------------------------------------------------------


def test_one_account_in_every_event_stays_single_wave():
    """1 hot PLAIN account in 100% of events: balance adds commute and
    non-limit validation never reads a balance, so the planner must keep
    the whole batch on ONE wave (no edges), bit-exact."""
    oracle, dev, ts = _pair()
    tr = [
        Transfer(id=5000 + i, debit_account_id=1,
                 credit_account_id=2 + i % 20, amount=1 + i % 3, ledger=1,
                 code=1)
        for i in range(64)
    ]
    probe = HazardTracker()
    decision, plan = probe.plan(transfers_to_np(tr))
    assert decision == "fast" and plan is None
    _check(oracle, dev, ts, tr)


def test_hot_limit_account_exhaustion_order():
    """A hot LIMIT account whose credit headroom runs out mid-batch: each
    touch is one wave deep (validation must see every prior touch), and
    the exact lane where exceeds_credits starts firing must match the
    strictly-serial oracle."""
    oracle, dev, ts = _pair(limit_accounts=(5,), funded=50)
    tr = []
    for i in range(12):  # 12 x 6 = 72 > 50: later lanes must fail
        tr.append(Transfer(id=6000 + i, debit_account_id=5,
                           credit_account_id=6 + i % 8, amount=6, ledger=1,
                           code=1))
        tr.append(Transfer(id=6100 + i, debit_account_id=2 + i % 3,
                           credit_account_id=10 + i % 8, amount=1, ledger=1,
                           code=1))
    probe = HazardTracker()
    probe.limit_account_ids = set(oracle.accounts) and {5}
    probe._limit_lo = np.array([5], dtype=np.uint64)
    decision, plan = probe.plan(transfers_to_np(tr))
    assert decision == "waves"
    assert plan.n_waves == 12  # one wave per limit touch
    ts = _check(oracle, dev, ts, tr)
    assert dev.hazards.plan_stats["waves"] >= 1


def test_hot_limit_chain_deeper_than_cap_falls_to_residue():
    """More touches of one limit account than WAVE_CAP: the tail falls to
    the serial residue (the escape hatch), results still bit-exact."""
    n = WAVE_CAP + 8
    oracle, dev, ts = _pair(n_accounts=48, limit_accounts=(5,),
                            funded=3 * n)
    tr = []
    for i in range(n):
        tr.append(Transfer(id=7000 + i, debit_account_id=5,
                           credit_account_id=6 + i % 8, amount=2, ledger=1,
                           code=1))
        tr.append(Transfer(id=7500 + i, debit_account_id=10 + i % 20,
                           credit_account_id=31 + i % 16, amount=1,
                           ledger=1, code=1))
    probe = HazardTracker()
    probe.limit_account_ids = {5}
    probe._limit_lo = np.array([5], dtype=np.uint64)
    decision, plan = probe.plan(transfers_to_np(tr))
    assert decision == "waves"
    assert plan.n_waves == WAVE_CAP
    assert plan.residue_n == 8  # the capped tail, in original order
    _check(oracle, dev, ts, tr)


def test_same_batch_pend_post_void_races():
    """post AND void of the same same-batch pending (first resolve wins),
    a post of a pending created LATER in the batch (not_found, creator
    still succeeds), and a void-then-post pair — all order semantics the
    waves must preserve exactly."""
    oracle, dev, ts = _pair()
    tr = [
        Transfer(id=8000, debit_account_id=1, credit_account_id=2,
                 amount=30, ledger=1, code=1, flags=F_PENDING),
        Transfer(id=8001, pending_id=8000, amount=30, flags=F_POST),
        Transfer(id=8002, pending_id=8000, flags=F_VOID),  # already posted
        # post BEFORE its creator: must fail not_found; creator succeeds
        Transfer(id=8003, pending_id=8010, amount=5, flags=F_POST),
        Transfer(id=8010, debit_account_id=3, credit_account_id=4,
                 amount=5, ledger=1, code=1, flags=F_PENDING),
        # void then post of another same-batch pending
        Transfer(id=8020, debit_account_id=5, credit_account_id=6,
                 amount=7, ledger=1, code=1, flags=F_PENDING),
        Transfer(id=8021, pending_id=8020, flags=F_VOID),
        Transfer(id=8022, pending_id=8020, amount=7, flags=F_POST),
    ] + [
        Transfer(id=8100 + i, debit_account_id=7 + i % 8,
                 credit_account_id=15 + i % 8, amount=1, ledger=1, code=1)
        for i in range(16)
    ]
    _check(oracle, dev, ts, tr)


def test_linked_chains_next_to_waves():
    """Linked chains (serial residue) coexisting with same-batch two-phase
    waves; a post referencing a CHAIN-created pending must be pulled into
    the residue with its creator (entanglement closure)."""
    oracle, dev, ts = _pair()
    tr = [
        # chain creating a pending, then failing -> rollback
        Transfer(id=9000, debit_account_id=1, credit_account_id=2,
                 amount=5, ledger=1, code=1,
                 flags=F_LINKED | F_PENDING),
        Transfer(id=9001, debit_account_id=1, credit_account_id=2,
                 amount=0, ledger=1, code=1),  # breaks the chain
        # post of the rolled-back pending: must see not_found
        Transfer(id=9002, pending_id=9000, amount=5, flags=F_POST),
        # a healthy chain
        Transfer(id=9010, debit_account_id=3, credit_account_id=4,
                 amount=2, ledger=1, code=1, flags=F_LINKED),
        Transfer(id=9011, debit_account_id=3, credit_account_id=4,
                 amount=2, ledger=1, code=1),
    ] + [
        t
        for i in range(8)
        for t in (
            Transfer(id=9100 + i, debit_account_id=5 + i % 6,
                     credit_account_id=11 + i % 6, amount=9, ledger=1,
                     code=1, flags=F_PENDING),
            Transfer(id=9200 + i, pending_id=9100 + i, amount=4,
                     flags=F_POST),
        )
    ]
    probe = HazardTracker()
    decision, plan = probe.plan(transfers_to_np(tr))
    assert decision == "waves"
    assert plan.wave_of[2] < 0  # the chain-pending post joined the residue
    assert plan.n_waves >= 2  # the healthy pairs still wave
    _check(oracle, dev, ts, tr)


def test_duplicate_id_first_occurrence_fails():
    """Duplicate-id group where occurrence 1 FAILS validation: occurrence
    2 must then succeed, occurrence 3 must see exists — the waves walk
    the group in lane order."""
    oracle, dev, ts = _pair()
    tr = [
        Transfer(id=9500, debit_account_id=1, credit_account_id=1,
                 amount=1, ledger=1, code=1),  # accounts equal: fails
        Transfer(id=9500, debit_account_id=1, credit_account_id=2,
                 amount=1, ledger=1, code=1),  # now succeeds
        Transfer(id=9500, debit_account_id=1, credit_account_id=2,
                 amount=2, ledger=1, code=1),  # exists_with_different...
        Transfer(id=9500, debit_account_id=1, credit_account_id=2,
                 amount=1, ledger=1, code=1),  # exists
    ] + [
        Transfer(id=9600 + i, debit_account_id=3 + i % 10,
                 credit_account_id=13 + i % 10, amount=1, ledger=1, code=1)
        for i in range(12)
    ]
    _check(oracle, dev, ts, tr)


@pytest.mark.parametrize("seed", [31, 32, 33])
def test_zipfian_hot_mix_randomized_parity(seed):
    """Randomized zipfian hot-account batches with same-batch two-phase
    pairs, duplicate ids, limit-account traffic and occasional chains —
    run through auto dispatch; every batch bit-exact and the wave path
    demonstrably engaged."""
    rng = np.random.default_rng(seed)
    oracle, dev, ts = _pair(n_accounts=40, limit_accounts=(3,),
                            funded=10_000)
    next_id = 20_000
    for _ in range(5):
        tr = []
        n_pairs = 6
        for i in range(n_pairs):
            tr.append(Transfer(
                id=next_id + i, debit_account_id=1,
                credit_account_id=int(rng.integers(4, 40)), amount=3,
                ledger=1, code=1, flags=F_PENDING,
            ))
        for i in range(n_pairs):
            tr.append(Transfer(
                id=next_id + 100 + i, pending_id=next_id + i,
                amount=0 if i % 2 else 3, flags=F_POST if i % 3 else F_VOID,
            ))
        for i in range(36):
            # zipf-ish: most traffic on accounts 1-3 (3 is limited)
            u = float(rng.random())
            a = 1 + int(39 * u**4)
            b = int(rng.integers(1, 41))
            if b == a:
                b = a % 40 + 1
            tr.append(Transfer(
                id=next_id + 200 + i, debit_account_id=a,
                credit_account_id=b, amount=1 + int(rng.integers(0, 3)),
                ledger=1, code=1,
            ))
        if rng.random() < 0.6:  # occasional duplicate id
            tr.append(Transfer(id=next_id + 200, debit_account_id=2,
                               credit_account_id=5, amount=1, ledger=1,
                               code=1))
        if rng.random() < 0.5:  # occasional chain
            tr.append(Transfer(id=next_id + 300, debit_account_id=6,
                               credit_account_id=7, amount=2, ledger=1,
                               code=1, flags=F_LINKED))
            tr.append(Transfer(id=next_id + 301, debit_account_id=6,
                               credit_account_id=7, amount=2, ledger=1,
                               code=1))
        ts = _check(oracle, dev, ts, tr)
        next_id += 1000
    assert dev.hazards.plan_stats["waves"] >= 3, dev.hazards.plan_stats


# ----------------------------------------------------------------------
# plumbing: decision on the handle, metrics catalog, stats compat
# ----------------------------------------------------------------------


def test_handle_plan_and_wave_metrics():
    """The wave decision rides the commit_async handle (replica surfaces
    it as commit.group.wave_*), the waves.* metrics are registered under
    CATALOG'd names, and split_stats stays a readable compat view."""
    from tigerbeetle_tpu.state_machine import StateMachine

    dev = DeviceLedger(process=TEST_PROCESS, mode="auto")
    metrics = Metrics()
    dev.instrument(metrics, dev.tracer)
    sm = StateMachine(dev)
    ts = 10_000
    accounts = [Account(id=i, ledger=1, code=1) for i in range(1, 12)]
    ts += len(accounts)
    dev.execute_dense(Operation.create_accounts, ts, accounts)
    tr = [
        Transfer(id=100 + i, debit_account_id=1, credit_account_id=2 + i % 9,
                 amount=2, ledger=1, code=1, flags=F_PENDING)
        for i in range(8)
    ] + [
        Transfer(id=200 + i, pending_id=100 + i, flags=F_POST)
        for i in range(8)
    ] + [
        Transfer(id=300 + i, debit_account_id=2 + i % 9,
                 credit_account_id=3 + i % 8 if 3 + i % 8 != 2 + i % 9
                 else 11, amount=1, ledger=1, code=1)
        for i in range(16)
    ]
    body = transfers_to_np(tr).tobytes()
    ts += len(tr)
    handle = sm.commit_async(Operation.create_transfers, ts, body)
    plan = sm.handle_plan(handle)
    assert plan is not None and plan[0] == "waves" and plan[1] >= 2
    assert sm.commit_finish(handle) == b""  # all-success
    # waves.* metrics live under CATALOG'd names
    names = {
        c.name for c in metrics._counters.values()
    } | {g.name for g in metrics._gauges.values()} | {
        h.name for h in metrics._histograms.values()
    }
    wave_names = {n for n in names if n.startswith("waves.")}
    assert {"waves.batches", "waves.per_batch", "waves.chain_len_max",
            "waves.occupancy"} <= wave_names
    assert all(n in CATALOG for n in wave_names), wave_names - set(CATALOG)
    # legacy stat surface: same dict, legacy keys present
    s = dict(dev.hazards.split_stats)
    for key in ("fast", "fast_pv", "serial", "split", "split_pv", "waves"):
        assert key in s, s


def test_simulator_seed_matrix_with_waves():
    """Same seed, conflict-heavy workload, REAL device backend: two runs
    are byte-identical (the full stats dict, which folds in the committed
    history via the checker) — the wave planner introduces no
    nondeterminism under consensus, crashes included."""
    from tigerbeetle_tpu.testing.simulator import run_simulation

    kwargs = dict(
        ticks=220,
        backend_factory=None,  # the DeviceLedger (wave planner live)
        n_clients=1,
        crash_probability=0.002,
        workload_knobs={
            "conflict_rate": 0.3,
            "two_phase_rate": 0.35,
            "chain_rate": 0.1,
            "limit_account_rate": 0.2,
        },
    )
    a = run_simulation(17, **kwargs)
    b = run_simulation(17, **kwargs)
    assert a == b
    assert a["committed_ops"] > 3
