"""Secondary index trees + equality queries (reference: per-field index
trees, src/lsm/groove.zig:137-157 / src/state_machine.zig:103-206 tree ids
1-24; range scans src/lsm/tree.zig:1126-1140).

Two layers under test:
- LSM: Groove index maintenance (insert/upsert-diff/remove, composite
  keys) and Tree.range across flush/compaction, vs a dict model.
- Device: DeviceLedger.query_accounts/query_transfers — vectorized filter
  scan over HBM merged with the LSM index over the spilled tail — vs the
  oracle's full store.
"""

import random

import pytest

from tests.test_spill import _forest, run_spill_parity
from tigerbeetle_tpu.constants import TEST_PROCESS
from tigerbeetle_tpu.lsm.groove import TRANSFER_INDEX_FIELDS, Groove
from tigerbeetle_tpu.lsm.tree import Tree
from tigerbeetle_tpu.models.ledger import DeviceLedger
from tigerbeetle_tpu.models.oracle import OracleStateMachine
from tigerbeetle_tpu.testing.workload import WorkloadGenerator


def _mkrow(rng, ledger, code, dr, cr, amount, ts):
    """A 128-byte wire row with the given indexed fields."""
    row = bytearray(rng.randbytes(16))  # id
    row += dr.to_bytes(16, "little")
    row += cr.to_bytes(16, "little")
    row += amount.to_bytes(16, "little")
    row += rng.randbytes(16)  # pending_id
    row += rng.randbytes(16)  # user_data_128
    row += rng.randbytes(8) + rng.randbytes(4)  # ud64, ud32
    row += (0).to_bytes(4, "little")  # timeout
    row += ledger.to_bytes(4, "little")
    row += code.to_bytes(2, "little") + (0).to_bytes(2, "little")
    row += ts.to_bytes(8, "little")
    assert len(row) == 128
    return bytes(row)


def test_tree_range_scan():
    _, forest = _forest()
    tree = Tree(forest.grid, key_size=8, value_size=8, memtable_max=32)
    model = {}
    rng = random.Random(7)
    for i in range(600):
        k = rng.randrange(2000).to_bytes(8, "big")
        v = rng.getrandbits(60).to_bytes(8, "big")
        tree.put(k, v)
        model[k] = v
        if i % 9 == 5:
            tree.remove(k)
            model.pop(k)
    for lo_i, hi_i in [(0, 1999), (100, 300), (1500, 1501), (50, 50), (1990, 3000)]:
        lo = lo_i.to_bytes(8, "big")
        hi = min(hi_i, (1 << 63)).to_bytes(8, "big")
        expect = sorted((k, v) for k, v in model.items() if lo <= k <= hi)
        assert tree.range(lo, hi) == expect, (lo_i, hi_i)


def test_groove_index_maintenance():
    """insert/upsert-diff/remove keep every index tree consistent with a
    dict model, across memtable flushes and compactions."""
    _, forest = _forest()
    g = Groove(forest.grid, memtable_max=64,
               index_fields=TRANSFER_INDEX_FIELDS)
    rng = random.Random(11)
    rows: dict[int, tuple[int, bytes]] = {}  # id -> (ts, row)
    next_ts = 1
    for step in range(500):
        action = rng.random()
        if action < 0.6 or not rows:
            id_ = rng.getrandbits(64) | 1
            ts = next_ts
            next_ts += 1
            row = _mkrow(rng, ledger=rng.randint(1, 3), code=rng.randint(1, 5),
                         dr=rng.randint(1, 8), cr=rng.randint(1, 8),
                         amount=rng.randint(1, 6), ts=ts)
            g.insert(id_, ts, row)
            rows[id_] = (ts, row)
        elif action < 0.85:
            id_ = rng.choice(list(rows))
            ts, old = rows[id_]
            new = _mkrow(rng, ledger=rng.randint(1, 3), code=rng.randint(1, 5),
                         dr=rng.randint(1, 8), cr=rng.randint(1, 8),
                         amount=rng.randint(1, 6), ts=ts)
            new = old[:16] + new[16:]  # keep id bytes
            g.upsert(id_, ts, new, old_row=old)
            rows[id_] = (ts, new)
        else:
            id_ = rng.choice(list(rows))
            ts, old = rows[id_]
            g.remove(id_, ts, row=old)
            del rows[id_]
    g.flush()
    for field, lo_v, hi_v in (("ledger", 1, 3), ("code", 1, 5),
                              ("amount", 1, 6), ("debit_account_id", 1, 8)):
        off, w = g.index_spec[field]
        for v in range(lo_v, hi_v + 1):
            expect = sorted(
                ts for ts, row in rows.values()
                if int.from_bytes(row[off : off + w], "little") == v
            )
            assert g.query(field, v) == expect, (field, v)


def _oracle_query(oracle, store: str, field: str, value: int):
    objs = (oracle.accounts if store == "acct" else oracle.transfers).values()
    return sorted(
        (o for o in objs if getattr(o, field) == value),
        key=lambda o: o.timestamp,
    )


def test_device_query_parity_no_spill():
    """Filter-scan queries over a resident-only ledger vs the oracle."""
    oracle = OracleStateMachine()
    dev = DeviceLedger(process=TEST_PROCESS, mode="auto")
    gen = WorkloadGenerator(21, ledgers=(1, 2, 3), invalid_rate=0.05)
    ts = 1_000_000_000
    for b in range(8):
        op, events = (
            gen.gen_accounts_batch(40) if b % 3 == 0
            else gen.gen_transfers_batch(40)
        )
        ts += len(events)
        assert oracle.execute_dense(op, ts, events) == dev.execute_dense(
            op, ts, events
        )
    for field in ("ledger", "code"):
        for v in (1, 2, 3, 77):
            assert dev.query_accounts(field, v) == _oracle_query(
                oracle, "acct", field, v
            ), (field, v)
    some_acct = next(iter(oracle.accounts))
    for field, v in (
        ("ledger", 1), ("ledger", 2), ("code", 50),
        ("debit_account_id", some_acct), ("credit_account_id", some_acct),
        ("amount", 1), ("timeout", 0), ("pending_id", 0),
    ):
        assert dev.query_transfers(field, v) == _oracle_query(
            oracle, "xfer", field, v
        ), (field, v)


def test_device_query_parity_with_spill():
    """Queries must see spilled rows via the LSM index trees and resident
    rows via the device scan, deduped where stale LSM copies exist."""
    oracle, dev, _ = run_spill_parity(22, n_transfer_batches=52)
    assert dev.spill.stats["cycles"] >= 1
    some_acct = next(iter(oracle.accounts))
    checks = [
        ("ledger", 1),
        ("code", 7), ("code", 50),
        ("debit_account_id", some_acct), ("credit_account_id", some_acct),
        ("amount", 1), ("user_data_32", 0),
    ]
    for field, v in checks:
        got = dev.query_transfers(field, v)
        want = _oracle_query(oracle, "xfer", field, v)
        assert got == want, (field, v, len(got), len(want))
    # at least one checked query must have included a spilled row
    spilled_hit = any(
        any(t.id in dev.spill.spilled for t in _oracle_query(oracle, "xfer", f, v))
        for f, v in checks
    )
    assert spilled_hit


def test_query_value_range_checks():
    dev = DeviceLedger(process=TEST_PROCESS)
    with pytest.raises(ValueError):
        dev.query_transfers("code", 1 << 16)
    with pytest.raises(ValueError):
        dev.query_accounts("ledger", 1 << 32)
    with pytest.raises(KeyError):
        dev.query_transfers("flags", 1)  # not indexed (reference: ignored)
