"""Scenario tests for the oracle state machine.

Scenarios adapted from the reference's state machine unit tests
(reference: src/state_machine.zig test suite — create_accounts/create_transfers
result codes, linked chains, two-phase commits, balancing transfers).
"""

from tigerbeetle_tpu.constants import NS_PER_S, U64_MAX, U128_MAX
from tigerbeetle_tpu.models.oracle import OracleStateMachine
from tigerbeetle_tpu.types import (
    Account,
    AccountFlags,
    CreateAccountResult as AR,
    CreateTransferResult as TR,
    Operation,
    Transfer,
    TransferFlags as F,
)

LEDGER = 7


def make_machine(n_accounts=4, flags=(0, 0, 0, 0), ledgers=None):
    sm = OracleStateMachine()
    accounts = [
        Account(id=i + 1, ledger=(ledgers[i] if ledgers else LEDGER), code=1,
                flags=flags[i] if i < len(flags) else 0)
        for i in range(n_accounts)
    ]
    codes = sm.execute_dense(Operation.create_accounts, 100, accounts)
    assert codes == [0] * n_accounts
    return sm


def run_transfers(sm, transfers, timestamp=10_000):
    return sm.execute_dense(Operation.create_transfers, timestamp, transfers)


# --- create_accounts ---


def test_create_account_validation_precedence():
    sm = OracleStateMachine()
    cases = [
        (Account(id=1, ledger=1, code=1, reserved=5), AR.reserved_field),
        (Account(id=1, ledger=1, code=1, flags=1 << 5), AR.reserved_flag),
        (Account(id=0, ledger=1, code=1), AR.id_must_not_be_zero),
        (Account(id=U128_MAX, ledger=1, code=1), AR.id_must_not_be_int_max),
        (Account(id=1, ledger=1, code=1, flags=6), AR.flags_are_mutually_exclusive),
        (Account(id=1, ledger=1, code=1, debits_pending=1), AR.debits_pending_must_be_zero),
        (Account(id=1, ledger=1, code=1, debits_posted=1), AR.debits_posted_must_be_zero),
        (Account(id=1, ledger=1, code=1, credits_pending=1), AR.credits_pending_must_be_zero),
        (Account(id=1, ledger=1, code=1, credits_posted=1), AR.credits_posted_must_be_zero),
        (Account(id=1, ledger=0, code=1), AR.ledger_must_not_be_zero),
        (Account(id=1, ledger=1, code=0), AR.code_must_not_be_zero),
        # precedence: reserved_field beats id checks
        (Account(id=0, ledger=0, code=0, reserved=9), AR.reserved_field),
    ]
    events = [c[0] for c in cases]
    codes = sm.execute_dense(Operation.create_accounts, 50, events)
    assert codes == [int(c[1]) for c in cases]


def test_create_account_exists_codes():
    sm = OracleStateMachine()
    base = Account(id=9, ledger=1, code=2, user_data_128=5, user_data_64=6, user_data_32=7)
    import dataclasses as dc

    variants = [
        dc.replace(base),
        dc.replace(base, flags=int(AccountFlags.debits_must_not_exceed_credits)),
        dc.replace(base, user_data_128=0),
        dc.replace(base, user_data_64=0),
        dc.replace(base, user_data_32=0),
        dc.replace(base, ledger=3),
        dc.replace(base, code=3),
        dc.replace(base),
    ]
    codes = sm.execute_dense(Operation.create_accounts, 50, variants)
    assert codes == [
        0,
        AR.exists_with_different_flags,
        AR.exists_with_different_user_data_128,
        AR.exists_with_different_user_data_64,
        AR.exists_with_different_user_data_32,
        AR.exists_with_different_ledger,
        AR.exists_with_different_code,
        AR.exists,
    ]
    assert sm.accounts[9].timestamp == 50 - 8 + 1  # first event's timestamp


def test_account_timestamps_assigned_per_event():
    sm = OracleStateMachine()
    events = [Account(id=i + 1, ledger=1, code=1) for i in range(3)]
    sm.execute_dense(Operation.create_accounts, 1000, events)
    assert [sm.accounts[i + 1].timestamp for i in range(3)] == [998, 999, 1000]


def test_account_timestamp_must_be_zero():
    sm = OracleStateMachine()
    codes = sm.execute_dense(
        Operation.create_accounts, 10, [Account(id=1, ledger=1, code=1, timestamp=5)]
    )
    assert codes == [AR.timestamp_must_be_zero]


# --- create_transfers: validation ---


def test_create_transfer_validation_codes():
    sm = make_machine()
    t = lambda **kw: Transfer(
        id=kw.pop("id", 100),
        debit_account_id=kw.pop("dr", 1),
        credit_account_id=kw.pop("cr", 2),
        amount=kw.pop("amount", 10),
        ledger=kw.pop("ledger", LEDGER),
        code=kw.pop("code", 1),
        **kw,
    )
    cases = [
        (t(flags=1 << 7), TR.reserved_flag),
        (t(id=0), TR.id_must_not_be_zero),
        (t(id=U128_MAX), TR.id_must_not_be_int_max),
        (t(dr=0), TR.debit_account_id_must_not_be_zero),
        (t(dr=U128_MAX), TR.debit_account_id_must_not_be_int_max),
        (t(cr=0), TR.credit_account_id_must_not_be_zero),
        (t(cr=U128_MAX), TR.credit_account_id_must_not_be_int_max),
        (t(cr=1), TR.accounts_must_be_different),
        (t(pending_id=5), TR.pending_id_must_be_zero),
        (t(timeout=5), TR.timeout_reserved_for_pending_transfer),
        (t(amount=0), TR.amount_must_not_be_zero),
        (t(ledger=0), TR.ledger_must_not_be_zero),
        (t(code=0), TR.code_must_not_be_zero),
        (t(dr=999), TR.debit_account_not_found),
        (t(cr=999), TR.credit_account_not_found),
        (t(ledger=LEDGER + 1), TR.transfer_must_have_the_same_ledger_as_accounts),
        (t(id=101), TR.ok),
    ]
    codes = run_transfers(sm, [c[0] for c in cases])
    assert codes == [int(c[1]) for c in cases]
    assert sm.accounts[1].debits_posted == 10
    assert sm.accounts[2].credits_posted == 10


def test_accounts_must_have_same_ledger():
    sm = make_machine(ledgers=[1, 2, 1, 1])
    codes = run_transfers(
        sm, [Transfer(id=50, debit_account_id=1, credit_account_id=2, amount=1,
                      ledger=1, code=1)]
    )
    assert codes == [TR.accounts_must_have_the_same_ledger]


def test_transfer_exists_codes():
    sm = make_machine()
    base = Transfer(id=70, debit_account_id=1, credit_account_id=2, amount=9,
                    ledger=LEDGER, code=3, user_data_64=4)
    import dataclasses as dc

    batch = [
        base,
        dc.replace(base, flags=int(F.pending)),
        dc.replace(base, debit_account_id=3),
        dc.replace(base, credit_account_id=3),
        dc.replace(base, amount=8),
        dc.replace(base, user_data_128=1),
        dc.replace(base, user_data_64=1),
        dc.replace(base, user_data_32=1),
        dc.replace(base, code=9),
        dc.replace(base),
    ]
    codes = run_transfers(sm, batch)
    assert codes == [
        0,
        TR.exists_with_different_flags,
        TR.exists_with_different_debit_account_id,
        TR.exists_with_different_credit_account_id,
        TR.exists_with_different_amount,
        TR.exists_with_different_user_data_128,
        TR.exists_with_different_user_data_64,
        TR.exists_with_different_user_data_32,
        TR.exists_with_different_code,
        TR.exists,
    ]
    # exists does not double-apply balances
    assert sm.accounts[1].debits_posted == 9


def test_exists_with_different_timeout():
    sm = make_machine()
    p = Transfer(id=70, debit_account_id=1, credit_account_id=2, amount=9,
                 ledger=LEDGER, code=3, flags=int(F.pending), timeout=10)
    import dataclasses as dc

    codes = run_transfers(sm, [p, dc.replace(p, timeout=11)])
    assert codes == [0, TR.exists_with_different_timeout]


# --- two-phase ---


def test_two_phase_post_full():
    sm = make_machine()
    pend = Transfer(id=1000, debit_account_id=1, credit_account_id=2, amount=50,
                    ledger=LEDGER, code=1, flags=int(F.pending))
    assert run_transfers(sm, [pend], timestamp=10_000) == [0]
    assert sm.accounts[1].debits_pending == 50
    assert sm.accounts[2].credits_pending == 50

    post = Transfer(id=1001, pending_id=1000, amount=0,
                    flags=int(F.post_pending_transfer))
    assert run_transfers(sm, [post], timestamp=20_000) == [0]
    a1, a2 = sm.accounts[1], sm.accounts[2]
    assert (a1.debits_pending, a1.debits_posted) == (0, 50)
    assert (a2.credits_pending, a2.credits_posted) == (0, 50)
    e = sm.transfers[1001]
    assert e.amount == 50
    assert e.debit_account_id == 1 and e.credit_account_id == 2
    assert e.ledger == LEDGER and e.code == 1
    assert sm.posted[sm.transfers[1000].timestamp] == 1


def test_two_phase_post_partial_and_errors():
    sm = make_machine()
    pend = Transfer(id=1000, debit_account_id=1, credit_account_id=2, amount=50,
                    ledger=LEDGER, code=1, flags=int(F.pending))
    run_transfers(sm, [pend], timestamp=10_000)

    cases = [
        (Transfer(id=1, pending_id=1000,
                  flags=int(F.post_pending_transfer | F.void_pending_transfer)),
         TR.flags_are_mutually_exclusive),
        (Transfer(id=1, pending_id=1000, flags=int(F.post_pending_transfer | F.pending)),
         TR.flags_are_mutually_exclusive),
        (Transfer(id=1, pending_id=0, flags=int(F.post_pending_transfer)),
         TR.pending_id_must_not_be_zero),
        (Transfer(id=1, pending_id=U128_MAX, flags=int(F.post_pending_transfer)),
         TR.pending_id_must_not_be_int_max),
        (Transfer(id=1, pending_id=1, flags=int(F.post_pending_transfer)),
         TR.pending_id_must_be_different),
        (Transfer(id=1, pending_id=1000, timeout=5, flags=int(F.post_pending_transfer)),
         TR.timeout_reserved_for_pending_transfer),
        (Transfer(id=1, pending_id=4242, flags=int(F.post_pending_transfer)),
         TR.pending_transfer_not_found),
        (Transfer(id=1, pending_id=1000, debit_account_id=3, flags=int(F.post_pending_transfer)),
         TR.pending_transfer_has_different_debit_account_id),
        (Transfer(id=1, pending_id=1000, credit_account_id=3, flags=int(F.post_pending_transfer)),
         TR.pending_transfer_has_different_credit_account_id),
        (Transfer(id=1, pending_id=1000, ledger=LEDGER + 1, flags=int(F.post_pending_transfer)),
         TR.pending_transfer_has_different_ledger),
        (Transfer(id=1, pending_id=1000, code=99, flags=int(F.post_pending_transfer)),
         TR.pending_transfer_has_different_code),
        (Transfer(id=1, pending_id=1000, amount=51, flags=int(F.post_pending_transfer)),
         TR.exceeds_pending_transfer_amount),
        (Transfer(id=1, pending_id=1000, amount=49, flags=int(F.void_pending_transfer)),
         TR.pending_transfer_has_different_amount),
        # partial post ok:
        (Transfer(id=2000, pending_id=1000, amount=30, flags=int(F.post_pending_transfer)),
         TR.ok),
        # second post: already posted
        (Transfer(id=2001, pending_id=1000, amount=10, flags=int(F.post_pending_transfer)),
         TR.pending_transfer_already_posted),
    ]
    codes = run_transfers(sm, [c[0] for c in cases], timestamp=20_000)
    assert codes == [int(c[1]) for c in cases]
    a1, a2 = sm.accounts[1], sm.accounts[2]
    assert (a1.debits_pending, a1.debits_posted) == (0, 30)
    assert (a2.credits_pending, a2.credits_posted) == (0, 30)


def test_two_phase_void_and_not_pending():
    sm = make_machine()
    batch = [
        Transfer(id=1, debit_account_id=1, credit_account_id=2, amount=5,
                 ledger=LEDGER, code=1, flags=int(F.pending)),
        Transfer(id=2, debit_account_id=1, credit_account_id=2, amount=5,
                 ledger=LEDGER, code=1),
    ]
    assert run_transfers(sm, batch, timestamp=100) == [0, 0]
    void = Transfer(id=3, pending_id=1, flags=int(F.void_pending_transfer))
    not_pending = Transfer(id=4, pending_id=2, flags=int(F.void_pending_transfer))
    voided_again = Transfer(id=5, pending_id=1, flags=int(F.post_pending_transfer))
    codes = run_transfers(sm, [void, not_pending, voided_again], timestamp=200)
    assert codes == [0, TR.pending_transfer_not_pending, TR.pending_transfer_already_voided]
    a1 = sm.accounts[1]
    assert (a1.debits_pending, a1.debits_posted) == (0, 5)


def test_two_phase_expired():
    sm = make_machine()
    pend = Transfer(id=1, debit_account_id=1, credit_account_id=2, amount=5,
                    ledger=LEDGER, code=1, flags=int(F.pending), timeout=1)
    assert run_transfers(sm, [pend], timestamp=1000) == [0]
    p_ts = sm.transfers[1].timestamp
    post = Transfer(id=2, pending_id=1, flags=int(F.post_pending_transfer))
    codes = run_transfers(sm, [post], timestamp=p_ts + NS_PER_S)
    assert codes == [TR.pending_transfer_expired]
    codes = run_transfers(
        sm, [Transfer(id=3, pending_id=1, flags=int(F.post_pending_transfer))],
        timestamp=p_ts + NS_PER_S - 1,
    )
    assert codes == [0]


def test_post_exists_codes():
    sm = make_machine()
    run_transfers(sm, [Transfer(id=1, debit_account_id=1, credit_account_id=2,
                                amount=50, ledger=LEDGER, code=1, flags=int(F.pending))],
                  timestamp=100)
    post = Transfer(id=10, pending_id=1, amount=20, user_data_64=5,
                    flags=int(F.post_pending_transfer))
    import dataclasses as dc

    batch = [
        post,
        # void with amount 20 < p.amount 50 fails the pre-exists amount check
        # (reference: :950-952 runs before the exists lookup at :954).
        dc.replace(post, flags=int(F.void_pending_transfer)),
        dc.replace(post, amount=19),
        dc.replace(post, amount=0),  # t.amount==0: e.amount(20) != p.amount(50)
        dc.replace(post),
        dc.replace(post, user_data_64=0),  # e.ud64=5 != p.ud64=0
        dc.replace(post, user_data_64=7),
    ]
    codes = run_transfers(sm, batch, timestamp=200)
    assert codes == [
        0,
        TR.pending_transfer_has_different_amount,
        TR.exists_with_different_amount,
        TR.exists_with_different_amount,
        TR.exists,
        TR.exists_with_different_user_data_64,
        TR.exists_with_different_user_data_64,
    ]


# --- balancing transfers (reference: src/state_machine.zig:826-846) ---


def test_balancing_debit():
    sm = make_machine()
    # Give account 1 credits_posted=100 by a transfer 2->1.
    run_transfers(sm, [Transfer(id=1, debit_account_id=2, credit_account_id=1,
                                amount=100, ledger=LEDGER, code=1)], timestamp=100)
    # balancing_debit with amount=0 -> clamps to credits_posted - debits = 100.
    t = Transfer(id=2, debit_account_id=1, credit_account_id=3, amount=0,
                 ledger=LEDGER, code=1, flags=int(F.balancing_debit))
    assert run_transfers(sm, [t], timestamp=200) == [0]
    assert sm.transfers[2].amount == 100
    assert sm.accounts[1].debits_posted == 100
    # now balance exhausted -> exceeds_credits
    t2 = Transfer(id=3, debit_account_id=1, credit_account_id=3, amount=10,
                  ledger=LEDGER, code=1, flags=int(F.balancing_debit))
    assert run_transfers(sm, [t2], timestamp=300) == [TR.exceeds_credits]


def test_balancing_credit_clamp():
    sm = make_machine()
    run_transfers(sm, [Transfer(id=1, debit_account_id=3, credit_account_id=2,
                                amount=40, ledger=LEDGER, code=1)], timestamp=100)
    # account 3 has debits_posted=40; balancing_credit clamps credit into 3 at 40.
    t = Transfer(id=2, debit_account_id=1, credit_account_id=3, amount=100,
                 ledger=LEDGER, code=1, flags=int(F.balancing_credit))
    assert run_transfers(sm, [t], timestamp=200) == [0]
    assert sm.transfers[2].amount == 40


# --- balance limit flags ---


def test_debits_must_not_exceed_credits():
    sm = make_machine(flags=(int(AccountFlags.debits_must_not_exceed_credits), 0, 0, 0))
    run_transfers(sm, [Transfer(id=1, debit_account_id=2, credit_account_id=1,
                                amount=30, ledger=LEDGER, code=1)], timestamp=100)
    ok = Transfer(id=2, debit_account_id=1, credit_account_id=2, amount=30,
                  ledger=LEDGER, code=1)
    over = Transfer(id=3, debit_account_id=1, credit_account_id=2, amount=1,
                    ledger=LEDGER, code=1)
    assert run_transfers(sm, [ok, over], timestamp=200) == [0, TR.exceeds_credits]


def test_credits_must_not_exceed_debits():
    sm = make_machine(flags=(0, int(AccountFlags.credits_must_not_exceed_debits), 0, 0))
    over = Transfer(id=1, debit_account_id=1, credit_account_id=2, amount=1,
                    ledger=LEDGER, code=1)
    assert run_transfers(sm, [over], timestamp=200) == [TR.exceeds_debits]


# --- overflow ---


def test_overflow_codes():
    sm = make_machine()
    big = Transfer(id=1, debit_account_id=1, credit_account_id=2,
                   amount=U128_MAX - 5, ledger=LEDGER, code=1)
    assert run_transfers(sm, [big], timestamp=100) == [0]
    t = Transfer(id=2, debit_account_id=1, credit_account_id=3, amount=10,
                 ledger=LEDGER, code=1)
    assert run_transfers(sm, [t], timestamp=200) == [TR.overflows_debits_posted]
    t3 = Transfer(id=3, debit_account_id=3, credit_account_id=2, amount=10,
                  ledger=LEDGER, code=1)
    assert run_transfers(sm, [t3], timestamp=300) == [TR.overflows_credits_posted]
    # pending overflow of debits (debits_pending + debits_posted)
    p = Transfer(id=4, debit_account_id=1, credit_account_id=3, amount=5,
                 ledger=LEDGER, code=1, flags=int(F.pending))
    assert run_transfers(sm, [p], timestamp=400) == [0]
    p2 = Transfer(id=5, debit_account_id=1, credit_account_id=3, amount=1,
                  ledger=LEDGER, code=1, flags=int(F.pending))
    assert run_transfers(sm, [p2], timestamp=500) == [TR.overflows_debits]


def test_overflows_timeout():
    sm = make_machine()
    t = Transfer(id=1, debit_account_id=1, credit_account_id=2, amount=1,
                 ledger=LEDGER, code=1, flags=int(F.pending), timeout=(1 << 32) - 1)
    ts = U64_MAX - 1000
    assert run_transfers(sm, [t], timestamp=ts) == [TR.overflows_timeout]


# --- linked chains (reference: src/state_machine.zig:612-698) ---


def test_linked_chain_all_succeed():
    sm = make_machine()
    batch = [
        Transfer(id=1, debit_account_id=1, credit_account_id=2, amount=10,
                 ledger=LEDGER, code=1, flags=int(F.linked)),
        Transfer(id=2, debit_account_id=2, credit_account_id=3, amount=10,
                 ledger=LEDGER, code=1),
    ]
    assert run_transfers(sm, batch) == [0, 0]
    assert sm.accounts[2].debits_posted == 10


def test_linked_chain_rollback():
    sm = make_machine()
    batch = [
        Transfer(id=1, debit_account_id=1, credit_account_id=2, amount=10,
                 ledger=LEDGER, code=1, flags=int(F.linked)),
        Transfer(id=2, debit_account_id=1, credit_account_id=1, amount=10,
                 ledger=LEDGER, code=1),  # fails: accounts_must_be_different
        Transfer(id=3, debit_account_id=1, credit_account_id=2, amount=7,
                 ledger=LEDGER, code=1),  # independent, succeeds
    ]
    codes = run_transfers(sm, batch)
    assert codes == [TR.linked_event_failed, TR.accounts_must_be_different, 0]
    assert 1 not in sm.transfers  # rolled back
    assert sm.accounts[1].debits_posted == 7


def test_linked_chain_failure_mid_chain_skips_rest():
    sm = make_machine()
    batch = [
        Transfer(id=1, debit_account_id=1, credit_account_id=2, amount=10,
                 ledger=LEDGER, code=1, flags=int(F.linked)),
        Transfer(id=0, debit_account_id=1, credit_account_id=2, amount=10,
                 ledger=LEDGER, code=1, flags=int(F.linked)),  # id==0 fails
        Transfer(id=3, debit_account_id=1, credit_account_id=2, amount=10,
                 ledger=LEDGER, code=1),  # chain tail: linked_event_failed
        Transfer(id=4, debit_account_id=1, credit_account_id=2, amount=4,
                 ledger=LEDGER, code=1),
    ]
    codes = run_transfers(sm, batch)
    assert codes == [
        TR.linked_event_failed,
        TR.id_must_not_be_zero,
        TR.linked_event_failed,
        0,
    ]
    assert sm.accounts[1].debits_posted == 4


def test_linked_event_chain_open():
    sm = make_machine()
    batch = [
        Transfer(id=1, debit_account_id=1, credit_account_id=2, amount=10,
                 ledger=LEDGER, code=1, flags=int(F.linked)),
        Transfer(id=2, debit_account_id=1, credit_account_id=2, amount=10,
                 ledger=LEDGER, code=1, flags=int(F.linked)),
    ]
    codes = run_transfers(sm, batch)
    assert codes == [TR.linked_event_failed, TR.linked_event_chain_open]
    assert sm.accounts[1].debits_posted == 0


def test_single_linked_event_chain_open():
    sm = make_machine()
    batch = [Transfer(id=1, debit_account_id=1, credit_account_id=2, amount=10,
                      ledger=LEDGER, code=1, flags=int(F.linked))]
    assert run_transfers(sm, batch) == [TR.linked_event_chain_open]


def test_two_chains_and_visibility():
    sm = make_machine()
    # Chain 1 rolls back; chain 2 must not see chain 1's insert (id reuse ok).
    batch = [
        Transfer(id=1, debit_account_id=1, credit_account_id=2, amount=10,
                 ledger=LEDGER, code=1, flags=int(F.linked)),
        Transfer(id=2, debit_account_id=1, credit_account_id=1, amount=10,
                 ledger=LEDGER, code=1),  # break chain 1
        Transfer(id=1, debit_account_id=1, credit_account_id=3, amount=6,
                 ledger=LEDGER, code=1, flags=int(F.linked)),  # id 1 again: no exists
        Transfer(id=3, debit_account_id=3, credit_account_id=1, amount=6,
                 ledger=LEDGER, code=1),
    ]
    codes = run_transfers(sm, batch)
    assert codes == [TR.linked_event_failed, TR.accounts_must_be_different, 0, 0]
    assert sm.transfers[1].credit_account_id == 3


def test_chain_sparse_result_order():
    sm = make_machine()
    batch = [
        Transfer(id=1, debit_account_id=1, credit_account_id=2, amount=10,
                 ledger=LEDGER, code=1, flags=int(F.linked)),
        Transfer(id=2, debit_account_id=1, credit_account_id=2, amount=10,
                 ledger=LEDGER, code=1, flags=int(F.linked)),
        Transfer(id=3, debit_account_id=1, credit_account_id=1, amount=1,
                 ledger=LEDGER, code=1),
    ]
    sparse = sm.execute(Operation.create_transfers, 10_000, batch)
    assert sparse == [
        (0, int(TR.linked_event_failed)),
        (1, int(TR.linked_event_failed)),
        (2, int(TR.accounts_must_be_different)),
    ]


def test_dup_id_in_batch_first_fails_second_succeeds():
    sm = make_machine()
    batch = [
        Transfer(id=5, debit_account_id=1, credit_account_id=999, amount=10,
                 ledger=LEDGER, code=1),  # credit_account_not_found
        Transfer(id=5, debit_account_id=1, credit_account_id=2, amount=10,
                 ledger=LEDGER, code=1),  # id free again -> ok
        Transfer(id=5, debit_account_id=1, credit_account_id=2, amount=10,
                 ledger=LEDGER, code=1),  # exists
    ]
    codes = run_transfers(sm, batch)
    assert codes == [TR.credit_account_not_found, 0, TR.exists]


# --- in-batch pending chains ---


def test_pending_created_and_posted_same_batch():
    sm = make_machine()
    batch = [
        Transfer(id=1, debit_account_id=1, credit_account_id=2, amount=50,
                 ledger=LEDGER, code=1, flags=int(F.pending)),
        Transfer(id=2, pending_id=1, amount=0, flags=int(F.post_pending_transfer)),
    ]
    assert run_transfers(sm, batch) == [0, 0]
    a1 = sm.accounts[1]
    assert (a1.debits_pending, a1.debits_posted) == (0, 50)


def test_lookup_accounts_and_transfers():
    sm = make_machine()
    run_transfers(sm, [Transfer(id=8, debit_account_id=1, credit_account_id=2,
                                amount=3, ledger=LEDGER, code=1)])
    found = sm.lookup_accounts([2, 424242, 1])
    assert [a.id for a in found] == [2, 1]
    assert found[1].debits_posted == 3
    ts = sm.lookup_transfers([8, 9])
    assert [t.id for t in ts] == [8]
    assert ts[0].amount == 3


def test_workload_generator_runs():
    from tigerbeetle_tpu.testing.workload import WorkloadGenerator

    gen = WorkloadGenerator(seed=7)
    sm = OracleStateMachine()
    ts = 0
    for _ in range(6):
        op, accounts = gen.gen_accounts_batch(50)
        ts += len(accounts)
        sm.execute_dense(op, ts, accounts)
        op, transfers = gen.gen_transfers_batch(200)
        ts += len(transfers)
        codes = sm.execute_dense(op, ts, transfers)
        assert len(codes) == 200
    # the workload must exercise both success and a diversity of errors
    assert sm.transfers and sm.accounts
    assert any(c == 0 for c in codes)
