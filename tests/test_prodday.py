"""Production-day harness (tigerbeetle_tpu/prodday.py): timeline DSL,
phase-aligned SLO scorer, the shared recovery probe, and the simulator
twin's same-seed byte-identity.

The expensive live soak (scripts/prodday.py against a real cluster) is
marked `slow`; tier-1 proves the deterministic core:
  - the smoke timeline (3 phases, one scripted primary kill) replayed
    twice at one seed yields byte-identical committed histories AND
    byte-identical scorecard JSON;
  - per-phase recorder slicing is exact (hand-built Metrics ring);
  - an intentionally-blown p99 budget scores FAIL with the dominant
    critical-path leg named on the row.
"""

import json

import pytest

from tigerbeetle_tpu.latency import LEGS
from tigerbeetle_tpu.metrics import FlightRecorder, Metrics
from tigerbeetle_tpu.prodday import (
    Event,
    Phase,
    RecoveryProbe,
    Timeline,
    offered_rate,
    production_day,
    run_sim_twin,
    scale_timeline,
    score,
    scorecard_json,
    slice_history,
    smoke_timeline,
)

SEED = 7


@pytest.fixture(scope="module")
def twin():
    """One smoke-timeline twin run, shared across the module's tests."""
    return run_sim_twin(smoke_timeline(), seed=SEED)


# -- timeline DSL ------------------------------------------------------


def test_offered_rate_shapes():
    ramp = Phase("r", 10.0, ("ramp", 100, 300), sim_ticks=100)
    assert offered_rate(ramp, 0.0) == 100
    assert offered_rate(ramp, 0.5) == 200
    assert offered_rate(ramp, 1.0) == 300
    steady = Phase("s", 10.0, ("steady", 250), sim_ticks=100)
    assert offered_rate(steady, 0.1) == offered_rate(steady, 0.9) == 250
    spike = Phase("f", 10.0, ("spike", 100, 900), sim_ticks=100)
    assert offered_rate(spike, 0.1) == 100  # before the crowd
    assert offered_rate(spike, 0.5) == 900  # middle third
    assert offered_rate(spike, 0.9) == 100  # after


def test_timeline_validation():
    p = Phase("a", 10.0, ("steady", 10), sim_ticks=100)
    with pytest.raises(ValueError):  # duplicate phase names
        Timeline("t", (p, p)).validate()
    with pytest.raises(ValueError):  # event outside the timeline
        Timeline("t", (p,), (Event(99.0, "kill_primary"),)).validate()
    with pytest.raises(ValueError):  # unknown event kind
        Timeline("t", (p,), (Event(1.0, "meteor"),)).validate()
    with pytest.raises(ValueError):  # malformed load tuple
        Phase("b", 10.0, ("ramp", 1), sim_ticks=10).validate()
    assert production_day().duration_s > 0
    assert smoke_timeline().total_sim_ticks == 1100


def test_phase_at_and_event_tick_mapping():
    tl = smoke_timeline()
    assert tl.phase_at(0.0)[0].name == "warm"
    assert tl.phase_at(12.0)[0].name == "storm"
    assert tl.phase_at(999.0)[0].name == "cool"  # clamps to the tail
    # the kill at 17s is 7s into the 15s storm phase (starts at 10s,
    # 500 ticks from tick 300): 300 + int(7/15*500) = 533
    assert tl.event_tick(Event(17.0, "kill_primary")) == 533


def test_scale_timeline_preserves_shape():
    tl = scale_timeline(production_day(), time=0.5, rate=2.0)
    base = production_day()
    assert tl.duration_s == pytest.approx(base.duration_s * 0.5)
    assert tl.total_sim_ticks == base.total_sim_ticks  # sim untouched
    assert [p.name for p in tl.phases] == [p.name for p in base.phases]
    assert [p.slo for p in tl.phases] == [p.slo for p in base.phases]
    assert tl.phases[1].load[1] == base.phases[1].load[1] * 2.0
    assert tl.events[0].at_s == pytest.approx(base.events[0].at_s * 0.5)


# -- recovery probe ----------------------------------------------------


def test_recovery_probe_requires_post_fault_proof():
    probe = RecoveryProbe()
    probe.arm(now=10.0, view=3, issue_seq=40)
    # a reply from the pre-fault view answering a pre-fault request is
    # TCP tail traffic, not proof of recovery
    assert probe.observe_reply(10.001, view=3, issue_seq=40) is None
    assert probe.armed
    # newer view proves a new primary served
    ms = probe.observe_reply(10.5, view=4, issue_seq=40)
    assert ms == pytest.approx(500.0)
    assert probe.recoveries_ms == [ms]
    assert not probe.armed
    # disarmed probe ignores traffic
    assert probe.observe_reply(11.0, view=9, issue_seq=99) is None


def test_recovery_probe_post_fault_issue_resolves():
    probe = RecoveryProbe()
    probe.arm(now=1.0, view=2, issue_seq=10)
    ms = probe.observe_reply(1.25, view=2, issue_seq=11)
    assert ms == pytest.approx(250.0)


def test_recovery_probe_overlapping_faults_measure_independently():
    """A second fault before the first resolves must not drop the
    first's measurement (gray stall + reset storm = compound outage:
    one reply can prove post-fault service for both, each window
    measured from its OWN arm time)."""
    probe = RecoveryProbe()
    probe.arm(now=10.0, view=3, issue_seq=100)   # gray
    # backlogged acks: pre-gray issues resolve nothing
    assert probe.observe_reply(12.0, view=3, issue_seq=99) is None
    probe.arm(now=17.0, view=3, issue_seq=140)   # reset storm
    assert probe.armed
    # first post-reset ack proves post-gray service too
    ms = probe.observe_reply(19.0, view=3, issue_seq=141)
    assert ms == pytest.approx(2000.0)  # the newest window
    assert probe.recoveries_ms == [
        pytest.approx(9000.0), pytest.approx(2000.0)
    ]
    assert not probe.armed
    # an intermediate proof resolves only the arms it covers
    probe.arm(now=30.0, view=5, issue_seq=200)
    probe.arm(now=31.0, view=5, issue_seq=260)
    assert probe.observe_reply(32.0, view=5, issue_seq=230) is not None
    assert probe.armed  # the seq-260 arm still waits
    assert probe.observe_reply(33.0, view=6, issue_seq=230) is not None
    assert not probe.armed


# -- per-phase slicing exactness ---------------------------------------


def test_slice_history_exact():
    m = Metrics()
    rec = FlightRecorder(m, capacity=16)
    rec.record(1.0)  # pre-mark entry: phase None
    rec.set_phase("warm", now_s=1.5)
    m.counter("x").add(3)
    rec.record(2.0)
    rec.record(3.0)
    rec.set_phase("storm", now_s=3.5)
    rec.record(4.0)
    slices = slice_history(rec.history())
    assert sorted(
        (k, len(v)) for k, v in slices.items()
        if k is not None
    ) == [("storm", 1), ("warm", 2)]
    assert len(slices[None]) == 1
    assert [e["t"] for e in slices["warm"]] == [2.0, 3.0]
    assert slices["storm"][0]["t"] == 4.0
    assert rec.phase_log == [(1.5, "warm"), (3.5, "storm")]
    # the mark itself is visible as a counter delta in the next entry
    assert slices["warm"][0]["counters"]["flight.marks"] == 1


def test_registry_swap_clamps():
    """The sim twin re-attaches the recorder across replica restarts:
    counter deltas and histogram windows must restart cleanly instead of
    going negative."""
    m1 = Metrics()
    rec = FlightRecorder(m1, capacity=8)
    m1.counter("c").add(100)
    m1.histogram("h_us").observe(50.0)
    rec.record(1.0)
    m2 = Metrics()  # fresh registry (restarted replica)
    m2.counter("c").add(7)
    m2.histogram("h_us").observe(10.0)
    rec.metrics = m2
    e = rec.record(2.0)
    assert e["counters"]["c"] == 7  # new registry's value, not -93
    assert e["histograms"]["h_us"]["count"] == 1


# -- the sim twin ------------------------------------------------------


def test_twin_same_seed_byte_identical(twin):
    again = run_sim_twin(smoke_timeline(), seed=SEED)
    assert twin["history_digest"] == again["history_digest"]
    assert twin["scorecard_json"] == again["scorecard_json"]
    assert twin["phase_log"] == again["phase_log"]


def test_twin_runs_the_script(twin):
    assert twin["scripted_kills"] == 1
    assert twin["stats"]["crashes"] >= 1
    assert twin["stats"]["committed_ops"] > 0
    assert [n for _t, n in twin["phase_log"]] == ["warm", "storm", "cool"]
    # every recorded entry after the first mark carries its phase
    phases = {e.get("phase") for e in twin["flight_history"]}
    assert {"warm", "storm", "cool"} <= phases


def test_twin_different_seed_diverges(twin):
    other = run_sim_twin(smoke_timeline(), seed=SEED + 1)
    assert twin["history_digest"] != other["history_digest"]


def test_twin_scorecard_rows_complete(twin):
    card = twin["scorecard"]
    assert card["timeline"] == "smoke"
    by = {(r["phase"], r["slo"]): r for r in card["rows"]}
    for name in ("warm", "storm", "cool"):
        row = by[(name, "p99_ms")]
        assert row["budget"] > 0
        assert row["measured"] is None or row["measured"] > 0
    zl = by[("*", "zero_lost")]
    assert zl["pass"] is True  # run() raising would have failed the test
    assert json.loads(twin["scorecard_json"]) == card


def test_blown_budget_fails_with_dominant_leg(twin):
    """Score the SAME deterministic run against an absurd p99 budget:
    the row must FAIL and name the dominant critical-path leg."""
    blown = smoke_timeline(p99_budget_ms=0.001)
    card = score(blown, slice_history(twin["flight_history"]),
                 checks={"ok": True})
    assert card["pass"] is False
    failed = [r for r in card["rows"]
              if r["pass"] is False and r["slo"] == "p99_ms"]
    assert failed, card
    for r in failed:
        assert r["measured"] > r["budget"]
        assert r["dominant_leg"] in LEGS
        assert 0.0 < r["dominant_leg_share"] <= 1.0
    # scoring is pure: same inputs, same bytes
    assert scorecard_json(card) == scorecard_json(
        score(blown, slice_history(twin["flight_history"]),
              checks={"ok": True})
    )


def test_score_no_data_rows_are_visible_not_green():
    tl = Timeline(
        "empty",
        (Phase("only", 5.0, ("steady", 10), sim_ticks=50,
               slo={"p99_ms": 100.0, "availability": 0.99}),),
        slo={"recovery_ms": 1000.0},
    ).validate()
    card = score(tl, {})
    assert card["pass"] is True  # nothing FAILED...
    assert card["no_data"] == 3  # ...but nothing silently passed either
    assert all(r["pass"] is None for r in card["rows"])


def test_score_recovery_slo():
    tl = Timeline(
        "r", (Phase("p", 5.0, ("steady", 10), sim_ticks=50),),
        slo={"recovery_ms": 1000.0},
    ).validate()
    ok = score(tl, {}, recoveries_ms=[400.0, 900.0], faults_armed=2)
    assert ok["rows"][0]["measured"] == 900.0
    assert ok["rows"][0]["pass"] is True
    late = score(tl, {}, recoveries_ms=[1500.0], faults_armed=1)
    assert late["rows"][0]["pass"] is False
    # an armed fault that never proved post-fault service IS a failure
    unresolved = score(tl, {}, recoveries_ms=[400.0], faults_armed=2)
    assert unresolved["rows"][0]["pass"] is False


# -- the live soak (10+ minutes; nightly/slow lane) --------------------


@pytest.mark.slow
def test_prodday_live_soak(tmp_path):
    """The full scripted day against a live --backend dual cluster:
    ramp, flash crowd, primary kill + disk-fault restart, gray primary,
    connection-reset storm, slow CDC consumer — ends with conservation,
    parity and the CDC audit green and a complete scorecard."""
    import os
    import sys

    sys.path.insert(
        0,
        os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "scripts"),
    )
    import importlib

    prodday_script = importlib.import_module("prodday")

    tl = scale_timeline(production_day(), time=2.0, rate=0.5)
    report = prodday_script.run_prodday(
        tl, n_sessions=24, conns=4, backend="dual", seed=3,
        tmpdir=str(tmp_path),
        log=lambda *a: print(*a, file=sys.stderr),
    )
    assert report["checks"]["conservation_ok"], report["conservation"]
    assert report["checks"]["parity_ok"], report["parity"]
    assert report["checks"]["cdc_dup_free"], report["cdc"]
    assert report["events"]["kills"] == 1
    assert report["events"]["restarts"] >= 1
    assert report["events"]["disk_fault_slots"]
    assert report["recoveries_ms"]
    card = report["scorecard"]
    assert {r["phase"] for r in card["rows"]} >= {
        p.name for p in tl.phases
    }
