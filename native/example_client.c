/* Example C program against a running cluster — demonstrates the tb_client
 * ABI without any Python (compile: gcc example_client.c -L. -ltb_native).
 *
 * Creates two accounts, moves 100 units, and prints the balances.
 * Usage: ./example_client host:port[,host:port...]
 */

#include <stdint.h>
#include <stdio.h>
#include <string.h>

#include "tb_client.h"

/* 128-byte wire layouts (tigerbeetle_tpu/types.py) */
#pragma pack(push, 1)
typedef struct {
  uint64_t id_lo, id_hi;
  uint64_t debits_pending_lo, debits_pending_hi;
  uint64_t debits_posted_lo, debits_posted_hi;
  uint64_t credits_pending_lo, credits_pending_hi;
  uint64_t credits_posted_lo, credits_posted_hi;
  uint64_t user_data_128_lo, user_data_128_hi;
  uint64_t user_data_64;
  uint32_t user_data_32, reserved, ledger;
  uint16_t code, flags;
  uint64_t timestamp;
} tb_account_t;

typedef struct {
  uint64_t id_lo, id_hi;
  uint64_t debit_account_id_lo, debit_account_id_hi;
  uint64_t credit_account_id_lo, credit_account_id_hi;
  uint64_t amount_lo, amount_hi;
  uint64_t pending_id_lo, pending_id_hi;
  uint64_t user_data_128_lo, user_data_128_hi;
  uint64_t user_data_64;
  uint32_t user_data_32, timeout, ledger;
  uint16_t code, flags;
  uint64_t timestamp;
} tb_transfer_t;

typedef struct {
  uint32_t index, result;
} tb_result_t;
#pragma pack(pop)

enum {
  OP_CREATE_ACCOUNTS = 128,
  OP_CREATE_TRANSFERS = 129,
  OP_LOOKUP_ACCOUNTS = 130,
};

int main(int argc, char **argv) {
  const char *addresses = argc > 1 ? argv[1] : "127.0.0.1:3001";
  uint8_t client_id[16] = {7, 7, 7, 7, 7, 7, 7, 7, 7, 7, 7, 7, 7, 7, 7, 1};

  tb_client_t *client;
  int rc = tb_client_init(&client, addresses, 0, 0, client_id);
  if (rc != 0) {
    fprintf(stderr, "init failed: %d\n", rc);
    return 1;
  }

  tb_account_t accounts[2];
  memset(accounts, 0, sizeof(accounts));
  accounts[0].id_lo = 901;
  accounts[0].ledger = 700;
  accounts[0].code = 10;
  accounts[1].id_lo = 902;
  accounts[1].ledger = 700;
  accounts[1].code = 10;

  uint8_t reply[8192];
  uint64_t reply_len = 0;
  rc = tb_client_request(client, OP_CREATE_ACCOUNTS, accounts,
                         sizeof(accounts), reply, sizeof(reply), &reply_len);
  if (rc != 0) return 2;
  for (uint64_t i = 0; i < reply_len / sizeof(tb_result_t); i++) {
    tb_result_t *r = (tb_result_t *)(reply + i * sizeof(tb_result_t));
    printf("account[%u]: result %u\n", r->index, r->result);
  }

  tb_transfer_t transfer;
  memset(&transfer, 0, sizeof(transfer));
  transfer.id_lo = 901;
  transfer.debit_account_id_lo = 901;
  transfer.credit_account_id_lo = 902;
  transfer.amount_lo = 100;
  transfer.ledger = 700;
  transfer.code = 10;
  rc = tb_client_request(client, OP_CREATE_TRANSFERS, &transfer,
                         sizeof(transfer), reply, sizeof(reply), &reply_len);
  if (rc != 0) return 3;
  printf("transfer: %s\n", reply_len == 0 ? "ok" : "failed");

  uint64_t ids[4] = {901, 0, 902, 0}; /* packed LE u128 ids */
  rc = tb_client_request(client, OP_LOOKUP_ACCOUNTS, ids, sizeof(ids), reply,
                         sizeof(reply), &reply_len);
  if (rc != 0) return 4;
  for (uint64_t i = 0; i < reply_len / sizeof(tb_account_t); i++) {
    tb_account_t *a = (tb_account_t *)(reply + i * sizeof(tb_account_t));
    printf("account %llu: debits_posted=%llu credits_posted=%llu\n",
           (unsigned long long)a->id_lo,
           (unsigned long long)a->debits_posted_lo,
           (unsigned long long)a->credits_posted_lo);
  }

  tb_client_deinit(client);
  return 0;
}
