// AEGIS-128L MAC checksum — the framework's storage/network checksum.
//
// TPU-native counterpart of the reference's vsr.checksum (reference:
// src/vsr/checksum.zig:1-55): an AEGIS-128L AEAD specialised into a MAC
// (zero key, zero nonce, data absorbed as associated data, empty secret
// message), producing a 128-bit tag. Hardware AES rounds via AES-NI.
//
// Validated against the reference's published test vectors
// (reference: src/vsr/checksum.zig:83-101):
//   checksum("")            == 0x49F174618255402DE6E7E3C40D60CC83
//   checksum(16 zero bytes) == 0x263ABED41C103361 65D15DD08DD42AF7 (LE u128)

#include <cstdint>
#include <cstring>
#include <wmmintrin.h>  // AES-NI

namespace {

struct State {
  __m128i s[8];
};

inline void update(State &st, __m128i m0, __m128i m1) {
  // S'0 = AESRound(S7, S0^M0); S'i = AESRound(S_{i-1}, S_i);
  // S'4 = AESRound(S3, S4^M1).  (AEGIS-128L spec Update.)
  __m128i t0 = _mm_aesenc_si128(st.s[7], _mm_xor_si128(st.s[0], m0));
  __m128i t1 = _mm_aesenc_si128(st.s[0], st.s[1]);
  __m128i t2 = _mm_aesenc_si128(st.s[1], st.s[2]);
  __m128i t3 = _mm_aesenc_si128(st.s[2], st.s[3]);
  __m128i t4 = _mm_aesenc_si128(st.s[3], _mm_xor_si128(st.s[4], m1));
  __m128i t5 = _mm_aesenc_si128(st.s[4], st.s[5]);
  __m128i t6 = _mm_aesenc_si128(st.s[5], st.s[6]);
  __m128i t7 = _mm_aesenc_si128(st.s[6], st.s[7]);
  st.s[0] = t0; st.s[1] = t1; st.s[2] = t2; st.s[3] = t3;
  st.s[4] = t4; st.s[5] = t5; st.s[6] = t6; st.s[7] = t7;
}

const uint8_t C0_BYTES[16] = {0x00, 0x01, 0x01, 0x02, 0x03, 0x05, 0x08, 0x0d,
                              0x15, 0x22, 0x37, 0x59, 0x90, 0xe9, 0x79, 0x62};
const uint8_t C1_BYTES[16] = {0xdb, 0x3d, 0x18, 0x55, 0x6d, 0xc2, 0x2f, 0xf1,
                              0x20, 0x11, 0x31, 0x42, 0x73, 0xb5, 0x28, 0xdd};

inline State init_zero_key() {
  const __m128i C0 = _mm_loadu_si128((const __m128i *)C0_BYTES);
  const __m128i C1 = _mm_loadu_si128((const __m128i *)C1_BYTES);
  const __m128i Z = _mm_setzero_si128();  // key = nonce = 0
  State st;
  st.s[0] = Z;   // key ^ nonce
  st.s[1] = C1;
  st.s[2] = C0;
  st.s[3] = C1;
  st.s[4] = Z;   // key ^ nonce
  st.s[5] = C0;  // key ^ C0
  st.s[6] = C1;  // key ^ C1
  st.s[7] = C0;  // key ^ C0
  for (int i = 0; i < 10; i++) update(st, Z, Z);  // Update(nonce, key)
  return st;
}

}  // namespace

extern "C" {

// checksum(data) -> 16 tag bytes (the u128 little-endian).
// `final_v_bits`: the second LE64 of the finalization length block
// (0 = AEAD-as-MAC with empty message — the reference's construction).
void tb_checksum_ex(const uint8_t *data, uint64_t len, uint64_t final_v_bits,
                    uint8_t out[16]) {
  // The 10-round zero-key init state is static per process (the reference
  // memoizes it the same way, reference: src/vsr/checksum.zig:43-52).
  static const State seed = init_zero_key();
  State st = seed;

  uint64_t off = 0;
  while (off + 32 <= len) {
    __m128i m0 = _mm_loadu_si128((const __m128i *)(data + off));
    __m128i m1 = _mm_loadu_si128((const __m128i *)(data + off + 16));
    update(st, m0, m1);
    off += 32;
  }
  if (off < len) {
    uint8_t pad[32] = {0};
    memcpy(pad, data + off, len - off);
    __m128i m0 = _mm_loadu_si128((const __m128i *)pad);
    __m128i m1 = _mm_loadu_si128((const __m128i *)(pad + 16));
    update(st, m0, m1);
  }

  // Finalize: t = S2 ^ (LE64(data_bits) || LE64(v)); 7x Update(t, t);
  // tag = S0^..^S6.
  uint64_t sizes[2] = {len * 8, final_v_bits};
  __m128i t = _mm_xor_si128(_mm_loadu_si128((const __m128i *)sizes), st.s[2]);
  for (int i = 0; i < 7; i++) update(st, t, t);
  __m128i tag = _mm_xor_si128(st.s[0], st.s[1]);
  tag = _mm_xor_si128(tag, st.s[2]);
  tag = _mm_xor_si128(tag, st.s[3]);
  tag = _mm_xor_si128(tag, st.s[4]);
  tag = _mm_xor_si128(tag, st.s[5]);
  tag = _mm_xor_si128(tag, st.s[6]);
  _mm_storeu_si128((__m128i *)out, tag);
}

void tb_checksum(const uint8_t *data, uint64_t len, uint8_t out[16]) {
  tb_checksum_ex(data, len, 0, out);
}

}  // extern "C"
