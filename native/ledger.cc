// Native host ledger engine — the durable path's commit kernel.
//
// The reference's state machine is a CPU engine (reference:
// src/state_machine.zig:612-1077: per-event create_account /
// create_transfer / post-void over hash-indexed object stores, with
// linked-chain scope rollback from src/lsm/groove.zig:990-1010). This is
// the TPU build's host twin of that engine, sharing exact result-code
// semantics with the JAX DeviceLedger and the Python oracle
// (models/oracle.py): the replicated durable server computes reply codes
// here at native speed, while the device ledger remains the TPU compute
// path (flagship batches, sharded mesh, HBM residency). Parity between
// the three is enforced by tests/test_native_ledger.py (golden tables +
// randomized differential runs).
//
// Design: flat open-addressing tables (power-of-2, linear probe,
// tombstones for chain-rollback deletes, grow at load 1/2) over the
// 128-byte little-endian wire rows — no per-object allocation, no
// pointer chasing; u128 arithmetic via __uint128_t with explicit
// overflow checks mirroring sum_overflows (reference:
// src/state_machine.zig:1152-1157).

#include <cstdint>
#include <cstring>
#include <cstdlib>
#include <vector>

namespace {

typedef unsigned __int128 u128;

constexpr uint64_t NS_PER_S = 1000000000ull;

inline u128 mk128(uint64_t lo, uint64_t hi) {
  return ((u128)hi << 64) | lo;
}

#pragma pack(push, 1)
struct AccountRow {
  uint64_t id_lo, id_hi;
  uint64_t debits_pending_lo, debits_pending_hi;
  uint64_t debits_posted_lo, debits_posted_hi;
  uint64_t credits_pending_lo, credits_pending_hi;
  uint64_t credits_posted_lo, credits_posted_hi;
  uint64_t user_data_128_lo, user_data_128_hi;
  uint64_t user_data_64;
  uint32_t user_data_32;
  uint32_t reserved;
  uint32_t ledger;
  uint16_t code;
  uint16_t flags;
  uint64_t timestamp;

  u128 id() const { return mk128(id_lo, id_hi); }
  u128 debits_pending() const { return mk128(debits_pending_lo, debits_pending_hi); }
  u128 debits_posted() const { return mk128(debits_posted_lo, debits_posted_hi); }
  u128 credits_pending() const { return mk128(credits_pending_lo, credits_pending_hi); }
  u128 credits_posted() const { return mk128(credits_posted_lo, credits_posted_hi); }
  void set_debits_pending(u128 v) { debits_pending_lo = (uint64_t)v; debits_pending_hi = (uint64_t)(v >> 64); }
  void set_debits_posted(u128 v) { debits_posted_lo = (uint64_t)v; debits_posted_hi = (uint64_t)(v >> 64); }
  void set_credits_pending(u128 v) { credits_pending_lo = (uint64_t)v; credits_pending_hi = (uint64_t)(v >> 64); }
  void set_credits_posted(u128 v) { credits_posted_lo = (uint64_t)v; credits_posted_hi = (uint64_t)(v >> 64); }
};

struct TransferRow {
  uint64_t id_lo, id_hi;
  uint64_t debit_account_id_lo, debit_account_id_hi;
  uint64_t credit_account_id_lo, credit_account_id_hi;
  uint64_t amount_lo, amount_hi;
  uint64_t pending_id_lo, pending_id_hi;
  uint64_t user_data_128_lo, user_data_128_hi;
  uint64_t user_data_64;
  uint32_t user_data_32;
  uint32_t timeout;
  uint32_t ledger;
  uint16_t code;
  uint16_t flags;
  uint64_t timestamp;

  u128 id() const { return mk128(id_lo, id_hi); }
  u128 debit_account_id() const { return mk128(debit_account_id_lo, debit_account_id_hi); }
  u128 credit_account_id() const { return mk128(credit_account_id_lo, credit_account_id_hi); }
  u128 amount() const { return mk128(amount_lo, amount_hi); }
  u128 pending_id() const { return mk128(pending_id_lo, pending_id_hi); }
  void set_amount(u128 v) { amount_lo = (uint64_t)v; amount_hi = (uint64_t)(v >> 64); }
};
#pragma pack(pop)

static_assert(sizeof(AccountRow) == 128, "wire layout");
static_assert(sizeof(TransferRow) == 128, "wire layout");

// Account flags (reference: src/tigerbeetle.zig:42-62).
constexpr uint16_t A_LINKED = 1 << 0;
constexpr uint16_t A_DR_NOT_EXCEED_CR = 1 << 1;
constexpr uint16_t A_CR_NOT_EXCEED_DR = 1 << 2;
constexpr uint16_t A_PADDING = (uint16_t)~0x7u;

// Transfer flags (reference: src/tigerbeetle.zig:91-104).
constexpr uint16_t T_LINKED = 1 << 0;
constexpr uint16_t T_PENDING = 1 << 1;
constexpr uint16_t T_POST = 1 << 2;
constexpr uint16_t T_VOID = 1 << 3;
constexpr uint16_t T_BAL_DR = 1 << 4;
constexpr uint16_t T_BAL_CR = 1 << 5;
constexpr uint16_t T_PADDING = (uint16_t)~0x3Fu;

// CreateAccountResult (reference: src/tigerbeetle.zig:109-144).
enum AR : uint32_t {
  AR_ok = 0, AR_linked_event_failed = 1, AR_linked_event_chain_open = 2,
  AR_timestamp_must_be_zero = 3, AR_reserved_field = 4, AR_reserved_flag = 5,
  AR_id_must_not_be_zero = 6, AR_id_must_not_be_int_max = 7,
  AR_flags_are_mutually_exclusive = 8,
  AR_debits_pending_must_be_zero = 9, AR_debits_posted_must_be_zero = 10,
  AR_credits_pending_must_be_zero = 11, AR_credits_posted_must_be_zero = 12,
  AR_ledger_must_not_be_zero = 13, AR_code_must_not_be_zero = 14,
  AR_exists_with_different_flags = 15,
  AR_exists_with_different_user_data_128 = 16,
  AR_exists_with_different_user_data_64 = 17,
  AR_exists_with_different_user_data_32 = 18,
  AR_exists_with_different_ledger = 19, AR_exists_with_different_code = 20,
  AR_exists = 21,
};

// CreateTransferResult (reference: src/tigerbeetle.zig:149-229).
enum TR : uint32_t {
  TR_ok = 0, TR_linked_event_failed = 1, TR_linked_event_chain_open = 2,
  TR_timestamp_must_be_zero = 3, TR_reserved_flag = 4,
  TR_id_must_not_be_zero = 5, TR_id_must_not_be_int_max = 6,
  TR_flags_are_mutually_exclusive = 7,
  TR_debit_account_id_must_not_be_zero = 8,
  TR_debit_account_id_must_not_be_int_max = 9,
  TR_credit_account_id_must_not_be_zero = 10,
  TR_credit_account_id_must_not_be_int_max = 11,
  TR_accounts_must_be_different = 12,
  TR_pending_id_must_be_zero = 13, TR_pending_id_must_not_be_zero = 14,
  TR_pending_id_must_not_be_int_max = 15, TR_pending_id_must_be_different = 16,
  TR_timeout_reserved_for_pending_transfer = 17,
  TR_amount_must_not_be_zero = 18,
  TR_ledger_must_not_be_zero = 19, TR_code_must_not_be_zero = 20,
  TR_debit_account_not_found = 21, TR_credit_account_not_found = 22,
  TR_accounts_must_have_the_same_ledger = 23,
  TR_transfer_must_have_the_same_ledger_as_accounts = 24,
  TR_pending_transfer_not_found = 25, TR_pending_transfer_not_pending = 26,
  TR_pending_transfer_has_different_debit_account_id = 27,
  TR_pending_transfer_has_different_credit_account_id = 28,
  TR_pending_transfer_has_different_ledger = 29,
  TR_pending_transfer_has_different_code = 30,
  TR_exceeds_pending_transfer_amount = 31,
  TR_pending_transfer_has_different_amount = 32,
  TR_pending_transfer_already_posted = 33,
  TR_pending_transfer_already_voided = 34,
  TR_pending_transfer_expired = 35,
  TR_exists_with_different_flags = 36,
  TR_exists_with_different_debit_account_id = 37,
  TR_exists_with_different_credit_account_id = 38,
  TR_exists_with_different_amount = 39,
  TR_exists_with_different_pending_id = 40,
  TR_exists_with_different_user_data_128 = 41,
  TR_exists_with_different_user_data_64 = 42,
  TR_exists_with_different_user_data_32 = 43,
  TR_exists_with_different_timeout = 44,
  TR_exists_with_different_code = 45,
  TR_exists = 46,
  TR_overflows_debits_pending = 47, TR_overflows_credits_pending = 48,
  TR_overflows_debits_posted = 49, TR_overflows_credits_posted = 50,
  TR_overflows_debits = 51, TR_overflows_credits = 52,
  TR_overflows_timeout = 53,
  TR_exceeds_credits = 54, TR_exceeds_debits = 55,
};

inline bool sum_overflows_128(u128 a, u128 b) {
  return a + b < a;  // wraparound detection
}
inline bool sum_overflows_64(uint64_t a, uint64_t b) {
  uint64_t out;
  return __builtin_add_overflow(a, b, &out);
}

inline uint64_t hash_u128(u128 id) {
  uint64_t lo = (uint64_t)id, hi = (uint64_t)(id >> 64);
  uint64_t x = lo ^ (hi * 0x9E3779B97F4A7C15ull + 0xD1B54A32D192ED03ull);
  x ^= x >> 33;
  x *= 0xFF51AFD7ED558CCDull;
  x ^= x >> 33;
  x *= 0xC4CEB9FE1A85EC53ull;
  x ^= x >> 33;
  return x;
}

constexpr size_t NIL = (size_t)-1;

// Flat open-addressing table over 128-byte rows keyed by the row's u128 id.
// Linear probe over a SEPARATE key lane (16 B/slot: four keys per cache
// line, so a probe chain rarely crosses one line) with a parallel state
// lane; the 128-byte row lane is touched only on hit/insert. Tombstones
// support chain-rollback deletes; grow at load 1/2. Keys of empty and
// tombstone slots are 0 (state disambiguates), so probes compare the key
// first and check state only on key match or termination.
template <typename Row>
struct Table {
  std::vector<u128> keys;
  std::vector<Row> rows;
  std::vector<uint8_t> st;  // 0 empty, 1 full, 2 tombstone
  uint64_t mask = 0;
  size_t used = 0;  // full + tombstones (probe-length bound)
  size_t live = 0;  // full

  void init(size_t cap_log2) {
    size_t cap = (size_t)1 << cap_log2;
    keys.assign(cap, 0);
    rows.assign(cap, Row{});
    st.assign(cap, 0);
    mask = cap - 1;
    used = live = 0;
  }

  inline void prefetch(u128 id) const {
    __builtin_prefetch(&keys[hash_u128(id) & mask]);
  }

  size_t find(u128 id) const {
    size_t i = hash_u128(id) & mask;
    while (true) {
      if (keys[i] == id && st[i] == 1) return i;
      if (st[i] == 0) return NIL;
      i = (i + 1) & mask;
    }
  }

  // find + remember the insert slot in ONE probe chain (the hot path does
  // a miss-lookup immediately followed by an insert of the same key).
  size_t find_or_prepare(u128 id, size_t *insert_slot) {
    size_t i = hash_u128(id) & mask;
    size_t tomb = NIL;
    while (true) {
      if (keys[i] == id && st[i] == 1) return i;
      if (st[i] == 0) {
        *insert_slot = tomb != NIL ? tomb : i;
        return NIL;
      }
      if (st[i] == 2 && tomb == NIL) tomb = i;
      i = (i + 1) & mask;
    }
  }

  // insert at a slot returned by find_or_prepare (key known absent).
  void insert_at(size_t i, u128 id, const Row &r) {
    if (st[i] != 2) used++;
    st[i] = 1;
    keys[i] = id;
    rows[i] = r;
    live++;
  }

  bool needs_grow() const { return (used + 1) * 2 > rows.size(); }

  // Slot to insert `id` at (reuses tombstones); id must be absent.
  size_t slot_for_insert(u128 id) {
    size_t i = hash_u128(id) & mask;
    size_t tomb = NIL;
    while (true) {
      if (st[i] == 0) return tomb != NIL ? tomb : i;
      if (st[i] == 2 && tomb == NIL) tomb = i;
      i = (i + 1) & mask;
    }
  }

  void insert(u128 id, const Row &r) {
    if ((used + 1) * 2 > rows.size()) grow();
    size_t i = slot_for_insert(id);
    if (st[i] != 2) used++;
    st[i] = 1;
    keys[i] = id;
    rows[i] = r;
    live++;
  }

  void erase_slot(size_t i) {
    st[i] = 2;
    keys[i] = 0;
    rows[i] = Row{};
    live--;
  }

  void grow() {
    std::vector<Row> old_rows;
    std::vector<uint8_t> old_st;
    old_rows.swap(rows);
    old_st.swap(st);
    size_t cap = old_rows.size() * 2;
    keys.assign(cap, 0);
    rows.assign(cap, Row{});
    st.assign(cap, 0);
    mask = cap - 1;
    used = live = 0;
    for (size_t i = 0; i < old_rows.size(); i++) {
      if (old_st[i] == 1) insert(old_rows[i].id(), old_rows[i]);
    }
  }
};

// Posted groove: pending timestamp -> POSTED(1) | VOIDED(2) (reference:
// src/state_machine.zig:185-198 PostedGrooveValue).
struct PostedTable {
  struct Entry { uint64_t ts; uint8_t val; uint8_t state; };
  std::vector<Entry> e;
  uint64_t mask = 0;
  size_t used = 0, live = 0;

  void init(size_t cap_log2) {
    e.assign((size_t)1 << cap_log2, Entry{0, 0, 0});
    mask = e.size() - 1;
    used = live = 0;
  }
  size_t find(uint64_t ts) const {
    size_t i = hash_u128((u128)ts) & mask;
    while (true) {
      if (e[i].state == 0) return NIL;
      if (e[i].state == 1 && e[i].ts == ts) return i;
      i = (i + 1) & mask;
    }
  }
  void insert(uint64_t ts, uint8_t val) {
    if ((used + 1) * 2 > e.size()) grow();
    size_t i = hash_u128((u128)ts) & mask;
    size_t tomb = NIL;
    while (true) {
      if (e[i].state == 0) break;
      if (e[i].state == 2 && tomb == NIL) tomb = i;
      i = (i + 1) & mask;
    }
    if (tomb != NIL) i = tomb; else used++;
    e[i] = Entry{ts, val, 1};
    live++;
  }
  void erase(uint64_t ts) {
    size_t i = find(ts);
    if (i != NIL) { e[i].state = 2; live--; }
  }
  void grow() {
    std::vector<Entry> old;
    old.swap(e);
    e.assign(old.size() * 2, Entry{0, 0, 0});
    mask = e.size() - 1;
    used = live = 0;
    for (auto &x : old) if (x.state == 1) insert(x.ts, x.val);
  }
};

// Linked-chain rollback scope (reference: src/lsm/groove.zig:990-1010 via
// models/oracle.py _Scope): prior values of mutated keys, restored in
// reverse on chain break.
struct Undo {
  enum Kind : uint8_t { ACCT, XFER, POSTED };
  struct Item {
    Kind kind;
    bool existed;
    u128 id;        // acct/xfer key
    uint64_t ts;    // posted key
    AccountRow acct;
    TransferRow xfer;
    uint8_t posted_val;
  };
  std::vector<Item> items;
  bool open = false;
};

struct Ledger {
  Table<AccountRow> accounts;
  Table<TransferRow> transfers;
  PostedTable posted;
  uint64_t commit_timestamp = 0;
  Undo scope;
};

void scope_note_account(Ledger &L, u128 id) {
  if (!L.scope.open) return;
  Undo::Item it{};
  it.kind = Undo::ACCT;
  it.id = id;
  size_t s = L.accounts.find(id);
  it.existed = s != NIL;
  if (it.existed) it.acct = L.accounts.rows[s];
  L.scope.items.push_back(it);
}

void scope_note_transfer(Ledger &L, u128 id) {
  if (!L.scope.open) return;
  Undo::Item it{};
  it.kind = Undo::XFER;
  it.id = id;
  size_t s = L.transfers.find(id);
  it.existed = s != NIL;
  if (it.existed) it.xfer = L.transfers.rows[s];
  L.scope.items.push_back(it);
}

void scope_note_posted(Ledger &L, uint64_t ts) {
  if (!L.scope.open) return;
  Undo::Item it{};
  it.kind = Undo::POSTED;
  it.ts = ts;
  size_t s = L.posted.find(ts);
  it.existed = s != NIL;
  if (it.existed) it.posted_val = L.posted.e[s].val;
  L.scope.items.push_back(it);
}

void scope_rollback(Ledger &L) {
  for (size_t k = L.scope.items.size(); k-- > 0;) {
    const Undo::Item &it = L.scope.items[k];
    switch (it.kind) {
      case Undo::ACCT: {
        size_t s = L.accounts.find(it.id);
        if (it.existed) {
          if (s != NIL) L.accounts.rows[s] = it.acct;
          else L.accounts.insert(it.id, it.acct);
        } else if (s != NIL) {
          L.accounts.erase_slot(s);
        }
        break;
      }
      case Undo::XFER: {
        size_t s = L.transfers.find(it.id);
        if (it.existed) {
          if (s != NIL) L.transfers.rows[s] = it.xfer;
          else L.transfers.insert(it.id, it.xfer);
        } else if (s != NIL) {
          L.transfers.erase_slot(s);
        }
        break;
      }
      case Undo::POSTED: {
        if (it.existed) {
          size_t s = L.posted.find(it.ts);
          if (s != NIL) L.posted.e[s].val = it.posted_val;
          else L.posted.insert(it.ts, it.posted_val);
        } else {
          L.posted.erase(it.ts);
        }
        break;
      }
    }
  }
  L.scope.items.clear();
}

// --- create_account (reference: src/state_machine.zig:738-777) ---

uint32_t create_account(Ledger &L, const AccountRow &a) {
  u128 id = a.id();
  if (a.reserved != 0) return AR_reserved_field;
  if (a.flags & A_PADDING) return AR_reserved_flag;
  if (id == 0) return AR_id_must_not_be_zero;
  if (id == ~(u128)0) return AR_id_must_not_be_int_max;
  if ((a.flags & A_DR_NOT_EXCEED_CR) && (a.flags & A_CR_NOT_EXCEED_DR))
    return AR_flags_are_mutually_exclusive;
  if (a.debits_pending() != 0) return AR_debits_pending_must_be_zero;
  if (a.debits_posted() != 0) return AR_debits_posted_must_be_zero;
  if (a.credits_pending() != 0) return AR_credits_pending_must_be_zero;
  if (a.credits_posted() != 0) return AR_credits_posted_must_be_zero;
  if (a.ledger == 0) return AR_ledger_must_not_be_zero;
  if (a.code == 0) return AR_code_must_not_be_zero;

  size_t s = L.accounts.find(id);
  if (s != NIL) {
    const AccountRow &e = L.accounts.rows[s];
    // reference: src/state_machine.zig:767-777
    if (a.flags != e.flags) return AR_exists_with_different_flags;
    if (a.user_data_128_lo != e.user_data_128_lo ||
        a.user_data_128_hi != e.user_data_128_hi)
      return AR_exists_with_different_user_data_128;
    if (a.user_data_64 != e.user_data_64)
      return AR_exists_with_different_user_data_64;
    if (a.user_data_32 != e.user_data_32)
      return AR_exists_with_different_user_data_32;
    if (a.ledger != e.ledger) return AR_exists_with_different_ledger;
    if (a.code != e.code) return AR_exists_with_different_code;
    return AR_exists;
  }

  scope_note_account(L, id);
  L.accounts.insert(id, a);
  L.commit_timestamp = a.timestamp;
  return AR_ok;
}

// --- post/void (reference: src/state_machine.zig:907-1077) ---

uint32_t post_or_void(Ledger &L, const TransferRow &t) {
  u128 id = t.id();
  if ((t.flags & T_POST) && (t.flags & T_VOID))
    return TR_flags_are_mutually_exclusive;
  if (t.flags & T_PENDING) return TR_flags_are_mutually_exclusive;
  if (t.flags & T_BAL_DR) return TR_flags_are_mutually_exclusive;
  if (t.flags & T_BAL_CR) return TR_flags_are_mutually_exclusive;

  u128 pid = t.pending_id();
  if (pid == 0) return TR_pending_id_must_not_be_zero;
  if (pid == ~(u128)0) return TR_pending_id_must_not_be_int_max;
  if (pid == id) return TR_pending_id_must_be_different;
  if (t.timeout != 0) return TR_timeout_reserved_for_pending_transfer;

  size_t ps = L.transfers.find(pid);
  if (ps == NIL) return TR_pending_transfer_not_found;
  TransferRow p = L.transfers.rows[ps];
  if (!(p.flags & T_PENDING)) return TR_pending_transfer_not_pending;

  size_t drs = L.accounts.find(p.debit_account_id());
  size_t crs = L.accounts.find(p.credit_account_id());
  // pending transfer's accounts exist (they were checked at its creation)
  AccountRow dr = L.accounts.rows[drs];
  AccountRow cr = L.accounts.rows[crs];

  if (t.debit_account_id() > 0 && t.debit_account_id() != p.debit_account_id())
    return TR_pending_transfer_has_different_debit_account_id;
  if (t.credit_account_id() > 0 && t.credit_account_id() != p.credit_account_id())
    return TR_pending_transfer_has_different_credit_account_id;
  if (t.ledger > 0 && t.ledger != p.ledger)
    return TR_pending_transfer_has_different_ledger;
  if (t.code > 0 && t.code != p.code)
    return TR_pending_transfer_has_different_code;

  u128 amount = t.amount() > 0 ? t.amount() : p.amount();
  if (amount > p.amount()) return TR_exceeds_pending_transfer_amount;
  if ((t.flags & T_VOID) && amount < p.amount())
    return TR_pending_transfer_has_different_amount;

  size_t es = L.transfers.find(id);
  if (es != NIL) {
    const TransferRow &e = L.transfers.rows[es];
    // reference: src/state_machine.zig:1016-1077
    if (t.flags != e.flags) return TR_exists_with_different_flags;
    if (t.amount() == 0) {
      if (e.amount() != p.amount()) return TR_exists_with_different_amount;
    } else if (t.amount() != e.amount()) {
      return TR_exists_with_different_amount;
    }
    if (t.pending_id() != e.pending_id())
      return TR_exists_with_different_pending_id;
    if (mk128(t.user_data_128_lo, t.user_data_128_hi) == 0) {
      if (e.user_data_128_lo != p.user_data_128_lo ||
          e.user_data_128_hi != p.user_data_128_hi)
        return TR_exists_with_different_user_data_128;
    } else if (t.user_data_128_lo != e.user_data_128_lo ||
               t.user_data_128_hi != e.user_data_128_hi) {
      return TR_exists_with_different_user_data_128;
    }
    if (t.user_data_64 == 0) {
      if (e.user_data_64 != p.user_data_64)
        return TR_exists_with_different_user_data_64;
    } else if (t.user_data_64 != e.user_data_64) {
      return TR_exists_with_different_user_data_64;
    }
    if (t.user_data_32 == 0) {
      if (e.user_data_32 != p.user_data_32)
        return TR_exists_with_different_user_data_32;
    } else if (t.user_data_32 != e.user_data_32) {
      return TR_exists_with_different_user_data_32;
    }
    return TR_exists;
  }

  size_t fs = L.posted.find(p.timestamp);
  if (fs != NIL) {
    return L.posted.e[fs].val == 1 ? TR_pending_transfer_already_posted
                                   : TR_pending_transfer_already_voided;
  }

  if (p.timeout > 0) {
    uint64_t timeout_ns = (uint64_t)p.timeout * NS_PER_S;
    if (t.timestamp >= p.timestamp + timeout_ns)
      return TR_pending_transfer_expired;
  }

  TransferRow t2{};
  t2.id_lo = t.id_lo; t2.id_hi = t.id_hi;
  t2.debit_account_id_lo = p.debit_account_id_lo;
  t2.debit_account_id_hi = p.debit_account_id_hi;
  t2.credit_account_id_lo = p.credit_account_id_lo;
  t2.credit_account_id_hi = p.credit_account_id_hi;
  if (mk128(t.user_data_128_lo, t.user_data_128_hi) > 0) {
    t2.user_data_128_lo = t.user_data_128_lo;
    t2.user_data_128_hi = t.user_data_128_hi;
  } else {
    t2.user_data_128_lo = p.user_data_128_lo;
    t2.user_data_128_hi = p.user_data_128_hi;
  }
  t2.user_data_64 = t.user_data_64 > 0 ? t.user_data_64 : p.user_data_64;
  t2.user_data_32 = t.user_data_32 > 0 ? t.user_data_32 : p.user_data_32;
  t2.ledger = p.ledger;
  t2.code = p.code;
  t2.pending_id_lo = t.pending_id_lo;
  t2.pending_id_hi = t.pending_id_hi;
  t2.timeout = 0;
  t2.timestamp = t.timestamp;
  t2.flags = t.flags;
  t2.set_amount(amount);

  scope_note_transfer(L, id);
  L.transfers.insert(id, t2);
  scope_note_posted(L, p.timestamp);
  L.posted.insert(p.timestamp, (t.flags & T_POST) ? 1 : 2);

  scope_note_account(L, dr.id());
  scope_note_account(L, cr.id());
  dr.set_debits_pending(dr.debits_pending() - p.amount());
  cr.set_credits_pending(cr.credits_pending() - p.amount());
  if (t.flags & T_POST) {
    dr.set_debits_posted(dr.debits_posted() + amount);
    cr.set_credits_posted(cr.credits_posted() + amount);
  }
  // drs/crs stay valid: only the TRANSFER/posted tables changed above
  L.accounts.rows[drs] = dr;
  L.accounts.rows[crs] = cr;

  L.commit_timestamp = t.timestamp;
  return TR_ok;
}

// --- create_transfer (reference: src/state_machine.zig:779-905) ---

uint32_t create_transfer(Ledger &L, const TransferRow &t) {
  u128 id = t.id();
  if (t.flags & T_PADDING) return TR_reserved_flag;
  if (id == 0) return TR_id_must_not_be_zero;
  if (id == ~(u128)0) return TR_id_must_not_be_int_max;

  if (t.flags & (T_POST | T_VOID)) return post_or_void(L, t);

  u128 dr_id = t.debit_account_id(), cr_id = t.credit_account_id();
  if (dr_id == 0) return TR_debit_account_id_must_not_be_zero;
  if (dr_id == ~(u128)0) return TR_debit_account_id_must_not_be_int_max;
  if (cr_id == 0) return TR_credit_account_id_must_not_be_zero;
  if (cr_id == ~(u128)0) return TR_credit_account_id_must_not_be_int_max;
  if (cr_id == dr_id) return TR_accounts_must_be_different;

  if (t.pending_id() != 0) return TR_pending_id_must_be_zero;
  if (!(t.flags & T_PENDING) && t.timeout != 0)
    return TR_timeout_reserved_for_pending_transfer;
  if (!(t.flags & (T_BAL_DR | T_BAL_CR)) && t.amount() == 0)
    return TR_amount_must_not_be_zero;

  if (t.ledger == 0) return TR_ledger_must_not_be_zero;
  if (t.code == 0) return TR_code_must_not_be_zero;

  size_t drs = L.accounts.find(dr_id);
  if (drs == NIL) return TR_debit_account_not_found;
  size_t crs = L.accounts.find(cr_id);
  if (crs == NIL) return TR_credit_account_not_found;
  AccountRow dr = L.accounts.rows[drs];
  AccountRow cr = L.accounts.rows[crs];

  if (dr.ledger != cr.ledger) return TR_accounts_must_have_the_same_ledger;
  if (t.ledger != dr.ledger)
    return TR_transfer_must_have_the_same_ledger_as_accounts;

  // An existing transfer must not influence overflow/limit checks
  // (reference: src/state_machine.zig:823-824). One probe chain resolves
  // both the exists check and (on miss) the insert slot — but the slot is
  // only reusable if no grow intervenes (checked below).
  size_t ins = NIL;
  if (L.transfers.needs_grow()) L.transfers.grow();
  size_t es = L.transfers.find_or_prepare(id, &ins);
  if (es != NIL) {
    const TransferRow &e = L.transfers.rows[es];
    // reference: src/state_machine.zig:886-905
    if (t.flags != e.flags) return TR_exists_with_different_flags;
    if (t.debit_account_id() != e.debit_account_id())
      return TR_exists_with_different_debit_account_id;
    if (t.credit_account_id() != e.credit_account_id())
      return TR_exists_with_different_credit_account_id;
    if (t.amount() != e.amount()) return TR_exists_with_different_amount;
    if (t.user_data_128_lo != e.user_data_128_lo ||
        t.user_data_128_hi != e.user_data_128_hi)
      return TR_exists_with_different_user_data_128;
    if (t.user_data_64 != e.user_data_64)
      return TR_exists_with_different_user_data_64;
    if (t.user_data_32 != e.user_data_32)
      return TR_exists_with_different_user_data_32;
    if (t.timeout != e.timeout) return TR_exists_with_different_timeout;
    if (t.code != e.code) return TR_exists_with_different_code;
    return TR_exists;
  }

  u128 amount = t.amount();
  if (t.flags & (T_BAL_DR | T_BAL_CR)) {
    if (amount == 0) amount = (u128)UINT64_MAX;  // reference: :829 (u64 max)
  }
  if (t.flags & T_BAL_DR) {
    u128 dr_balance = dr.debits_posted() + dr.debits_pending();
    u128 headroom = dr.credits_posted() > dr_balance
                        ? dr.credits_posted() - dr_balance : 0;
    if (headroom < amount) amount = headroom;
    if (amount == 0) return TR_exceeds_credits;
  }
  if (t.flags & T_BAL_CR) {
    u128 cr_balance = cr.credits_posted() + cr.credits_pending();
    u128 headroom = cr.debits_posted() > cr_balance
                        ? cr.debits_posted() - cr_balance : 0;
    if (headroom < amount) amount = headroom;
    if (amount == 0) return TR_exceeds_debits;
  }

  if (t.flags & T_PENDING) {
    if (sum_overflows_128(amount, dr.debits_pending()))
      return TR_overflows_debits_pending;
    if (sum_overflows_128(amount, cr.credits_pending()))
      return TR_overflows_credits_pending;
  }
  if (sum_overflows_128(amount, dr.debits_posted()))
    return TR_overflows_debits_posted;
  if (sum_overflows_128(amount, cr.credits_posted()))
    return TR_overflows_credits_posted;
  // debits_pending + debits_posted itself cannot wrap here: both were
  // built by guarded additions, so their true sum fits u128 only if...
  // it CAN wrap in adversarial snapshots; mirror the oracle's exact math
  // (python ints don't wrap): detect either partial or total wrap.
  if (sum_overflows_128(dr.debits_pending(), dr.debits_posted()) ||
      sum_overflows_128(amount, dr.debits_pending() + dr.debits_posted()))
    return TR_overflows_debits;
  if (sum_overflows_128(cr.credits_pending(), cr.credits_posted()) ||
      sum_overflows_128(amount, cr.credits_pending() + cr.credits_posted()))
    return TR_overflows_credits;

  if (sum_overflows_64(t.timestamp, (uint64_t)t.timeout * NS_PER_S))
    return TR_overflows_timeout;

  // reference: src/tigerbeetle.zig:31-39 balance limit flags
  if ((dr.flags & A_DR_NOT_EXCEED_CR) &&
      dr.debits_pending() + dr.debits_posted() + amount > dr.credits_posted())
    return TR_exceeds_credits;
  if ((cr.flags & A_CR_NOT_EXCEED_DR) &&
      cr.credits_pending() + cr.credits_posted() + amount > cr.debits_posted())
    return TR_exceeds_debits;

  TransferRow t2 = t;
  t2.set_amount(amount);
  scope_note_transfer(L, id);
  L.transfers.insert_at(ins, id, t2);  // slot from find_or_prepare above

  scope_note_account(L, dr_id);
  scope_note_account(L, cr_id);
  if (t.flags & T_PENDING) {
    dr.set_debits_pending(dr.debits_pending() + amount);
    cr.set_credits_pending(cr.credits_pending() + amount);
  } else {
    dr.set_debits_posted(dr.debits_posted() + amount);
    cr.set_credits_posted(cr.credits_posted() + amount);
  }
  // drs/crs stay valid: nothing touched the ACCOUNT table since find
  L.accounts.rows[drs] = dr;
  L.accounts.rows[crs] = cr;

  L.commit_timestamp = t.timestamp;
  return TR_ok;
}

}  // namespace

extern "C" {

void *tb_ledger_new(int acct_slots_log2, int xfer_slots_log2) {
  Ledger *L = new Ledger();
  L->accounts.init(acct_slots_log2 > 4 ? acct_slots_log2 : 4);
  L->transfers.init(xfer_slots_log2 > 4 ? xfer_slots_log2 : 4);
  L->posted.init(10);
  return L;
}

void tb_ledger_free(void *h) { delete (Ledger *)h; }

// Batch executor with linked chains (reference: src/state_machine.zig:
// 612-698 execute + scopes). op: 128=create_accounts, 129=create_transfers.
// events: n contiguous 128-byte wire rows. out: n dense u32 result codes.
// Returns the number of non-ok codes, or -1 on invalid arguments.
int64_t tb_ledger_execute(void *h, uint8_t op, const uint8_t *events,
                          uint32_t n, uint64_t timestamp, uint32_t *out) {
  Ledger &L = *(Ledger *)h;
  if (op != 128 && op != 129) return -1;
  int64_t failures = 0;
  int64_t chain = -1;
  bool chain_broken = false;

  for (uint32_t index = 0; index < n; index++) {
    const uint8_t *ev = events + (size_t)index * 128;
    // Software pipeline: pull the probe lines of a later event's keys
    // while this one executes (the tables are far larger than cache; the
    // probes are the only cold misses on the hot path).
    if (index + 4 < n) {
      const uint8_t *pv = events + (size_t)(index + 4) * 128;
      uint64_t plo, phi;
      memcpy(&plo, pv, 8);
      memcpy(&phi, pv + 8, 8);
      if (op == 129) {
        L.transfers.prefetch(mk128(plo, phi));
        uint64_t dlo, dhi, clo, chi;
        memcpy(&dlo, pv + 16, 8);
        memcpy(&dhi, pv + 24, 8);
        memcpy(&clo, pv + 32, 8);
        memcpy(&chi, pv + 40, 8);
        L.accounts.prefetch(mk128(dlo, dhi));
        L.accounts.prefetch(mk128(clo, chi));
      } else {
        L.accounts.prefetch(mk128(plo, phi));
      }
    }
    uint16_t flags;  // both row layouts: flags @118, timestamp @120
    memcpy(&flags, ev + 118, 2);
    uint64_t ev_ts;
    memcpy(&ev_ts, ev + 120, 8);
    uint32_t result = UINT32_MAX;  // sentinel: not yet decided

    if (flags & 0x1) {  // linked
      if (chain < 0) {
        chain = index;
        chain_broken = false;
        L.scope.open = true;
        L.scope.items.clear();
      }
      if (index == n - 1) result = 2;  // linked_event_chain_open
    }
    if (result == UINT32_MAX && chain_broken) result = 1;  // linked_event_failed
    if (result == UINT32_MAX && ev_ts != 0) result = 3;  // timestamp_must_be_zero

    if (result == UINT32_MAX) {
      uint64_t assigned = timestamp - n + index + 1;
      if (op == 128) {
        AccountRow a;
        memcpy(&a, ev, 128);
        a.timestamp = assigned;
        result = create_account(L, a);
      } else {
        TransferRow t;
        memcpy(&t, ev, 128);
        t.timestamp = assigned;
        result = create_transfer(L, t);
      }
    }

    out[index] = result;
    if (result != 0) {
      failures++;
      if (chain >= 0 && !chain_broken) {
        chain_broken = true;
        scope_rollback(L);
        L.scope.open = false;
        for (int64_t ci = chain; ci < (int64_t)index; ci++) {
          if (out[ci] == 0) { out[ci] = 1; failures++; }  // linked_event_failed
        }
      }
    }
    if (chain >= 0 && (!(flags & 0x1) || result == 2)) {
      if (!chain_broken) {
        L.scope.items.clear();  // persist
        L.scope.open = false;
      }
      chain = -1;
      chain_broken = false;
    }
  }
  return failures;
}

// Lookups (reference: src/state_machine.zig:701-736): found rows packed in
// request order, missing skipped. ids: n 16-byte little-endian u128s.
// Returns found count.
uint64_t tb_ledger_lookup(void *h, uint8_t op, const uint8_t *ids,
                          uint32_t n, uint8_t *out_rows) {
  Ledger &L = *(Ledger *)h;
  uint64_t found = 0;
  for (uint32_t i = 0; i < n; i++) {
    uint64_t lo, hi;
    memcpy(&lo, ids + (size_t)i * 16, 8);
    memcpy(&hi, ids + (size_t)i * 16 + 8, 8);
    u128 id = mk128(lo, hi);
    if (op == 130) {  // lookup_accounts
      size_t s = L.accounts.find(id);
      if (s != NIL) {
        memcpy(out_rows + found * 128, &L.accounts.rows[s], 128);
        found++;
      }
    } else if (op == 131) {  // lookup_transfers
      size_t s = L.transfers.find(id);
      if (s != NIL) {
        memcpy(out_rows + found * 128, &L.transfers.rows[s], 128);
        found++;
      }
    }
  }
  return found;
}

// Group execute (the fused-commit seam): k batches of `op` events applied
// back to back in ONE worker call — the replica fuses a quorum-ready run of
// prepares the way the reference pipelines commits (reference:
// src/vsr/replica.zig:3263-3315 commit_pipeline). events_k[j] points at
// batch j's ns[j] contiguous 128-byte rows; out_k[j] receives its dense
// codes; fails[j] its non-ok count. Returns 0, or -1 on invalid arguments.
int64_t tb_ledger_execute_group(void *h, uint8_t op,
                                const uint8_t *const *events_k,
                                const uint32_t *ns, const uint64_t *tss,
                                uint32_t k, uint32_t *const *out_k,
                                int64_t *fails) {
  for (uint32_t j = 0; j < k; j++) {
    int64_t f = tb_ledger_execute(h, op, events_k[j], ns[j], tss[j], out_k[j]);
    if (f < 0) return -1;
    fails[j] = f;
  }
  return 0;
}

// --- state fingerprint (the dual-commit parity seam) ---
// Order-independent digest over the LIVE table contents: sum (mod 2^64) of
// a per-row hash of the 128-byte wire image, so two engines with different
// slot layouts (this host table vs the device hash table) agree iff their
// logical row sets are bit-identical. The SAME function is implemented in
// JAX over the device tables (models/ledger.py state_fingerprint) — any
// constant here changes both or the dual-commit verification breaks loudly.

static inline uint64_t fp_mix(uint64_t x) {
  x ^= x >> 33;
  x *= 0xFF51AFD7ED558CCDull;
  x ^= x >> 33;
  x *= 0xC4CEB9FE1A85EC53ull;
  x ^= x >> 33;
  return x;
}

static inline uint64_t fp_row(const uint32_t *w) {
  uint64_t hsh = 0x9E3779B97F4A7C15ull;
  for (int i = 0; i < 32; i++) {
    hsh ^= (uint64_t)w[i] * 0xC2B2AE3D27D4EB4Full;
    hsh = ((hsh << 27) | (hsh >> 37)) * 0x9E3779B97F4A7C15ull +
          0x165667B19E3779F9ull;
  }
  return fp_mix(hsh);
}

// out8: [accounts_fp, transfers_fp, accounts_live, transfers_live,
//        posted_live, commit_timestamp, 0, 0]
void tb_ledger_fingerprint(void *h, uint64_t *out8) {
  Ledger &L = *(Ledger *)h;
  uint64_t afp = 0, tfp = 0;
  for (size_t i = 0; i < L.accounts.rows.size(); i++) {
    if (L.accounts.st[i] == 1)
      afp += fp_row((const uint32_t *)&L.accounts.rows[i]);
  }
  for (size_t i = 0; i < L.transfers.rows.size(); i++) {
    if (L.transfers.st[i] == 1)
      tfp += fp_row((const uint32_t *)&L.transfers.rows[i]);
  }
  out8[0] = afp;
  out8[1] = tfp;
  out8[2] = L.accounts.live;
  out8[3] = L.transfers.live;
  out8[4] = L.posted.live;
  out8[5] = L.commit_timestamp;
  out8[6] = 0;
  out8[7] = 0;
}

// counts: [n_accounts, n_transfers, n_posted, commit_timestamp]
void tb_ledger_counts(void *h, uint64_t *out4) {
  Ledger &L = *(Ledger *)h;
  out4[0] = L.accounts.live;
  out4[1] = L.transfers.live;
  out4[2] = L.posted.live;
  out4[3] = L.commit_timestamp;
}

// --- snapshot / restore (checkpoint blobs) ---
// Layout: 64-byte header {n_accounts, n_transfers, n_posted,
// commit_timestamp, acct_cap_log2, xfer_cap_log2, posted_cap_log2,
// reserved} (all u64) then account rows, transfer rows, posted pairs
// {ts u64, val u64}. Rows are emitted in TABLE SLOT ORDER and restore
// recreates the exact capacities, so identical histories — and
// restore-then-continue — produce byte-identical snapshots (the replica's
// cross-replica determinism contract).

uint64_t tb_ledger_snapshot_size(void *h) {
  Ledger &L = *(Ledger *)h;
  return 64 + (uint64_t)L.accounts.live * 128 +
         (uint64_t)L.transfers.live * 128 + (uint64_t)L.posted.live * 16;
}

void tb_ledger_snapshot(void *h, uint8_t *out) {
  Ledger &L = *(Ledger *)h;
  uint64_t head[8] = {L.accounts.live, L.transfers.live, L.posted.live,
                      L.commit_timestamp,
                      (uint64_t)__builtin_ctzll(L.accounts.rows.size()),
                      (uint64_t)__builtin_ctzll(L.transfers.rows.size()),
                      (uint64_t)__builtin_ctzll(L.posted.e.size()), 0};
  memcpy(out, head, 64);
  uint8_t *p = out + 64;
  for (size_t i = 0; i < L.accounts.rows.size(); i++) {
    if (L.accounts.st[i] == 1) {
      memcpy(p, &L.accounts.rows[i], 128);
      p += 128;
    }
  }
  for (size_t i = 0; i < L.transfers.rows.size(); i++) {
    if (L.transfers.st[i] == 1) {
      memcpy(p, &L.transfers.rows[i], 128);
      p += 128;
    }
  }
  for (size_t i = 0; i < L.posted.e.size(); i++) {
    if (L.posted.e[i].state == 1) {
      uint64_t pair[2] = {L.posted.e[i].ts, L.posted.e[i].val};
      memcpy(p, pair, 16);
      p += 16;
    }
  }
}

int tb_ledger_restore(void *h, const uint8_t *data, uint64_t len) {
  Ledger &L = *(Ledger *)h;
  if (len < 64) return -1;
  uint64_t head[8];
  memcpy(head, data, 64);
  uint64_t need = 64 + head[0] * 128 + head[1] * 128 + head[2] * 16;
  if (len < need) return -1;
  if (head[4] > 40 || head[5] > 40 || head[6] > 40) return -1;
  // exact source capacities: slot order (and thus the next snapshot's
  // bytes) reproduces across restore
  L.accounts.init(head[4]);
  L.transfers.init(head[5]);
  L.posted.init(head[6]);
  L.commit_timestamp = head[3];
  const uint8_t *p = data + 64;
  for (uint64_t i = 0; i < head[0]; i++) {
    AccountRow a;
    memcpy(&a, p, 128);
    p += 128;
    L.accounts.insert(a.id(), a);
  }
  for (uint64_t i = 0; i < head[1]; i++) {
    TransferRow t;
    memcpy(&t, p, 128);
    p += 128;
    L.transfers.insert(t.id(), t);
  }
  for (uint64_t i = 0; i < head[2]; i++) {
    uint64_t pair[2];
    memcpy(pair, p, 16);
    p += 16;
    L.posted.insert(pair[0], (uint8_t)pair[1]);
  }
  return 0;
}

}  // extern "C"
