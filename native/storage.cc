// Durable sector-aligned file IO — the storage layer under the WAL,
// superblock, and grid zones.
//
// TPU-native counterpart of the reference's Storage (reference:
// src/storage.zig:14-60): O_DIRECT + O_DSYNC where the filesystem supports
// it (bypassing the page cache so an fsync'd write is really on the device),
// with a buffered+fdatasync fallback otherwise. All IO is bounce-buffered
// through a sector-aligned scratch so callers may pass arbitrary pointers.

#include <cerrno>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <fcntl.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr uint64_t SECTOR = 4096;

struct Bounce {
  uint8_t *buf = nullptr;
  size_t cap = 0;
  ~Bounce() { free(buf); }
  uint8_t *get(size_t need) {
    if (cap < need) {
      free(buf);
      if (posix_memalign((void **)&buf, SECTOR, need) != 0) {
        buf = nullptr;
        cap = 0;
        return nullptr;
      }
      cap = need;
    }
    return buf;
  }
};

thread_local Bounce bounce;

inline uint64_t round_up(uint64_t x, uint64_t m) { return (x + m - 1) / m * m; }

}  // namespace

extern "C" {

// Open (or create) a data file of exactly `size` bytes. Tries O_DIRECT
// first; falls back to buffered IO (some filesystems, e.g. overlayfs/tmpfs,
// reject O_DIRECT). Returns fd >= 0, or -errno.
int tb_storage_open(const char *path, uint64_t size, int must_create) {
  int flags = O_RDWR | O_DSYNC | (must_create ? (O_CREAT | O_EXCL) : 0);
  int fd = open(path, flags | O_DIRECT, 0644);
  if (fd < 0 && (errno == EINVAL || errno == EOPNOTSUPP)) {
    // Some filesystems reject O_DIRECT only after creating the inode, so
    // the buffered retry must not O_EXCL-fail on the file the failed open
    // just created.
    int retry_flags = O_RDWR | O_DSYNC | (must_create ? O_CREAT : 0);
    fd = open(path, retry_flags, 0644);
  }
  if (fd < 0) return -errno;
  if (must_create) {
    if (ftruncate(fd, (off_t)size) != 0) {
      int e = errno;
      close(fd);
      return -e;
    }
  } else {
    struct stat st;
    if (fstat(fd, &st) != 0 || (uint64_t)st.st_size < size) {
      close(fd);
      return -EINVAL;
    }
  }
  return fd;
}

int tb_storage_close(int fd) { return close(fd) == 0 ? 0 : -errno; }

// Write `len` bytes at `offset` (both sector-multiples for the direct path;
// the bounce buffer provides memory alignment). Returns 0 or -errno.
int tb_storage_write(int fd, uint64_t offset, const void *data, uint64_t len) {
  uint64_t need = round_up(len, SECTOR);
  uint8_t *b = bounce.get(need);
  if (!b) return -ENOMEM;
  memcpy(b, data, len);
  if (need > len) memset(b + len, 0, need - len);
  uint64_t done = 0;
  while (done < need) {
    ssize_t n = pwrite(fd, b + done, need - done, (off_t)(offset + done));
    if (n < 0) {
      if (errno == EINTR) continue;
      return -errno;
    }
    done += (uint64_t)n;
  }
  return 0;
}

int tb_storage_read(int fd, uint64_t offset, void *data, uint64_t len) {
  uint64_t need = round_up(len, SECTOR);
  uint8_t *b = bounce.get(need);
  if (!b) return -ENOMEM;
  uint64_t done = 0;
  while (done < need) {
    ssize_t n = pread(fd, b + done, need - done, (off_t)(offset + done));
    if (n < 0) {
      if (errno == EINTR) continue;
      return -errno;
    }
    if (n == 0) break;  // short file tail reads as zeros
    done += (uint64_t)n;
  }
  if (done < need) memset(b + done, 0, need - done);
  memcpy(data, b, len);
  return 0;
}

int tb_storage_sync(int fd) { return fdatasync(fd) == 0 ? 0 : -errno; }

}  // extern "C"
