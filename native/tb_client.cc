// tb_client: the C-ABI client library over the TCP message bus.
//
// TPU-native counterpart of the reference's tb_client (reference:
// src/clients/c/tb_client.zig:8-27): a C interface any language can bind
// (the Python binding is tigerbeetle_tpu/client_ffi.py; see
// native/tb_client.h for the header). Protocol: 128-byte VSR headers with
// AEGIS-128L dual checksums (aegis.cc, same shared library), a register
// round trip establishing the session, then one in-flight request at a
// time with monotonically increasing request numbers — the reference
// client's session discipline (reference: src/vsr/client.zig:17-80).

#include "tb_client.h"

#include <arpa/inet.h>
#include <cerrno>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <netdb.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <sys/time.h>
#include <unistd.h>

extern "C" void tb_checksum(const uint8_t *data, uint64_t len, uint8_t out[16]);

namespace {

constexpr uint64_t HEADER_SIZE = 128;
constexpr uint64_t MESSAGE_SIZE_MAX = 1 << 20;

// header field offsets (tigerbeetle_tpu/vsr/header.py HEADER_DTYPE)
constexpr int OFF_CHECKSUM = 0;
constexpr int OFF_CHECKSUM_BODY = 16;
constexpr int OFF_CLIENT = 48;
constexpr int OFF_CONTEXT = 64;
constexpr int OFF_REQUEST = 80;
constexpr int OFF_CLUSTER = 84;
constexpr int OFF_OP = 96;
constexpr int OFF_TIMESTAMP = 112;
constexpr int OFF_SIZE = 120;
constexpr int OFF_COMMAND = 125;
constexpr int OFF_OPERATION = 126;

constexpr uint8_t COMMAND_REQUEST = 5;
constexpr uint8_t COMMAND_REPLY = 8;
constexpr uint8_t COMMAND_EVICTION = 18;
constexpr uint8_t OPERATION_REGISTER = 2;

int read_exact(int fd, uint8_t *buf, uint64_t len) {
  uint64_t done = 0;
  while (done < len) {
    ssize_t n = read(fd, buf + done, len - done);
    if (n < 0) {
      if (errno == EINTR) continue;
      return -errno;
    }
    if (n == 0) return -ECONNRESET;
    done += (uint64_t)n;
  }
  return 0;
}

int write_all(int fd, const uint8_t *buf, uint64_t len) {
  uint64_t done = 0;
  while (done < len) {
    ssize_t n = write(fd, buf + done, len - done);
    if (n < 0) {
      if (errno == EINTR) continue;
      return -errno;
    }
    done += (uint64_t)n;
  }
  return 0;
}

}  // namespace

extern "C" {

constexpr int ADDRS_MAX = 8;

struct tb_client {
  int fd;
  uint8_t client_id[16];
  uint64_t session;
  uint32_t request_number;
  uint32_t cluster;
  // The cluster's addresses: the client rotates to the next replica when a
  // request times out (it may be talking to a non-primary after a view
  // change; duplicate resends are answered from the replicated session
  // table, so rotation is idempotent). The reference client learns views
  // from pings instead — rotation is the blocking-client equivalent.
  char hosts[ADDRS_MAX][64];
  int ports[ADDRS_MAX];
  int addr_count;
  int addr_current;
};

// Build + send one request and block for its reply body.
static int submit(tb_client *c, uint8_t operation, uint32_t request_number,
                  const void *body, uint64_t body_len, void *reply,
                  uint64_t reply_cap, uint64_t *reply_len) {
  if (HEADER_SIZE + body_len > MESSAGE_SIZE_MAX) return -EMSGSIZE;
  uint8_t h[HEADER_SIZE];
  memset(h, 0, sizeof(h));
  memcpy(h + OFF_CLIENT, c->client_id, 16);
  uint64_t session = c->session;
  memcpy(h + OFF_CONTEXT, &session, 8);
  memcpy(h + OFF_REQUEST, &request_number, 4);
  memcpy(h + OFF_CLUSTER, &c->cluster, 4);
  uint32_t size = (uint32_t)(HEADER_SIZE + body_len);
  memcpy(h + OFF_SIZE, &size, 4);
  h[OFF_COMMAND] = COMMAND_REQUEST;
  h[OFF_OPERATION] = operation;
  tb_checksum((const uint8_t *)body, body_len, h + OFF_CHECKSUM_BODY);
  tb_checksum(h + 16, HEADER_SIZE - 16, h + OFF_CHECKSUM);

  int rc = write_all(c->fd, h, HEADER_SIZE);
  if (rc != 0) return rc;
  if (body_len) {
    rc = write_all(c->fd, (const uint8_t *)body, body_len);
    if (rc != 0) return rc;
  }

  // Await the matching reply (ignore anything else).
  for (;;) {
    uint8_t rh[HEADER_SIZE];
    rc = read_exact(c->fd, rh, HEADER_SIZE);
    if (rc != 0) return rc;
    uint32_t rsize;
    memcpy(&rsize, rh + OFF_SIZE, 4);
    if (rsize < HEADER_SIZE || rsize > MESSAGE_SIZE_MAX) return -EBADMSG;
    uint64_t blen = rsize - HEADER_SIZE;
    uint8_t *rbody = (uint8_t *)malloc(blen ? blen : 1);
    if (!rbody) return -ENOMEM;
    rc = read_exact(c->fd, rbody, blen);
    if (rc != 0) {
      free(rbody);
      return rc;
    }
    // checksum gate (header covered by [16,128); body by checksum_body)
    uint8_t want[16];
    tb_checksum(rh + 16, HEADER_SIZE - 16, want);
    if (memcmp(want, rh + OFF_CHECKSUM, 16) != 0) {
      free(rbody);
      continue;  // corrupt frame: skip
    }
    tb_checksum(rbody, blen, want);
    if (memcmp(want, rh + OFF_CHECKSUM_BODY, 16) != 0) {
      free(rbody);
      continue;
    }
    if (rh[OFF_COMMAND] == COMMAND_EVICTION) {
      free(rbody);
      return -ESTALE;  // session evicted
    }
    uint32_t rreq;
    memcpy(&rreq, rh + OFF_REQUEST, 4);
    if (rh[OFF_COMMAND] != COMMAND_REPLY || rreq != request_number) {
      free(rbody);
      continue;  // stale reply
    }
    if (blen > reply_cap) {
      free(rbody);
      return -ENOSPC;
    }
    memcpy(reply, rbody, blen);
    *reply_len = blen;
    free(rbody);
    return 0;
  }
}

static int connect_current(tb_client *c) {
  if (c->fd >= 0) {
    close(c->fd);
    c->fd = -1;
  }
  struct addrinfo hints, *res = nullptr;
  memset(&hints, 0, sizeof(hints));
  hints.ai_family = AF_INET;
  hints.ai_socktype = SOCK_STREAM;
  char portbuf[16];
  snprintf(portbuf, sizeof(portbuf), "%d", c->ports[c->addr_current]);
  if (getaddrinfo(c->hosts[c->addr_current], portbuf, &hints, &res) != 0 ||
      !res) {
    return -EHOSTUNREACH;
  }
  int fd = socket(res->ai_family, res->ai_socktype, res->ai_protocol);
  if (fd < 0 || connect(fd, res->ai_addr, res->ai_addrlen) != 0) {
    int e = errno;
    if (fd >= 0) close(fd);
    freeaddrinfo(res);
    return -(e ? e : EHOSTUNREACH);
  }
  freeaddrinfo(res);
  // Per-try timeout: long enough for first-commit jit compiles on a loaded
  // host, short enough that rotating to the real primary converges.
  struct timeval tv = {30, 0};
  setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  c->fd = fd;
  return 0;
}

// Submit with rotation: on timeout/reset, reconnect to the next replica and
// resend (duplicates are answered from the replicated session table).
static int submit_rotating(tb_client *c, uint8_t operation,
                           uint32_t request_number, const void *body,
                           uint64_t body_len, void *reply, uint64_t reply_cap,
                           uint64_t *reply_len) {
  int tries = c->addr_count * 6;
  int rc = -EHOSTUNREACH;
  for (int i = 0; i < tries; i++) {
    if (c->fd < 0) {
      rc = connect_current(c);
      if (rc != 0) {
        c->addr_current = (c->addr_current + 1) % c->addr_count;
        continue;
      }
    }
    rc = submit(c, operation, request_number, body, body_len, reply,
                reply_cap, reply_len);
    if (rc == 0 || rc == -ESTALE || rc == -ENOSPC || rc == -EMSGSIZE) {
      return rc;
    }
    // timeout / reset: rotate to the next replica
    close(c->fd);
    c->fd = -1;
    c->addr_current = (c->addr_current + 1) % c->addr_count;
  }
  return rc;
}

int tb_client_init(tb_client **out, const char *addresses, int port_unused,
                   uint32_t cluster, const uint8_t client_id[16]) {
  (void)port_unused;
  tb_client *c = (tb_client *)calloc(1, sizeof(tb_client));
  if (!c) return -ENOMEM;
  c->fd = -1;
  memcpy(c->client_id, client_id, 16);
  c->cluster = cluster;

  // parse "host:port[,host:port...]"
  const char *p = addresses;
  while (*p && c->addr_count < ADDRS_MAX) {
    const char *comma = strchr(p, ',');
    const char *end = comma ? comma : p + strlen(p);
    const char *colon = nullptr;
    for (const char *q = p; q < end; q++)
      if (*q == ':') colon = q;
    if (!colon) {
      free(c);
      return -EINVAL;
    }
    size_t hlen = (size_t)(colon - p);
    if (hlen == 0 || hlen >= sizeof(c->hosts[0])) {
      free(c);
      return -EINVAL;
    }
    memcpy(c->hosts[c->addr_count], p, hlen);
    c->hosts[c->addr_count][hlen] = 0;
    c->ports[c->addr_count] = atoi(colon + 1);
    c->addr_count++;
    p = comma ? comma + 1 : end;
  }
  if (c->addr_count == 0) {
    free(c);
    return -EINVAL;
  }

  // register the session (request 0, empty body)
  uint8_t session_buf[8];
  uint64_t n = 0;
  int rc = submit_rotating(c, OPERATION_REGISTER, 0, nullptr, 0, session_buf,
                           sizeof(session_buf), &n);
  if (rc != 0 || n < 8) {
    if (c->fd >= 0) close(c->fd);
    free(c);
    return rc != 0 ? rc : -EBADMSG;
  }
  memcpy(&c->session, session_buf, 8);
  c->request_number = 0;
  *out = c;
  return 0;
}

int tb_client_request(tb_client *c, uint8_t operation, const void *body,
                      uint64_t body_len, void *reply, uint64_t reply_cap,
                      uint64_t *reply_len) {
  if (c->session == 0) return -ESTALE;
  c->request_number += 1;
  return submit_rotating(c, operation, c->request_number, body, body_len,
                         reply, reply_cap, reply_len);
}

void tb_client_deinit(tb_client *c) {
  if (!c) return;
  if (c->fd >= 0) close(c->fd);
  free(c);
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Async packet client (the reference's packet/completion model, re-designed:
// src/clients/c/tb_client/packet.zig + thread.zig submit packets to a
// dedicated IO thread and get a completion callback). Here the context owns
// a POOL of sessions, each with its own blocking worker thread pulling from
// one shared packet FIFO — N requests genuinely in flight against the
// replica's commit window from ONE process, which is what the reference's
// single-session client gets from server-side pipelining. Same-operation
// create packets that fit one message are COALESCED into a single request
// and their sparse results demuxed back per packet (the reference's packet
// batching); lookups ride one packet per request (reply rows skip missing
// ids, so attribution needs the request body — not worth the ambiguity).
// ---------------------------------------------------------------------------

#include <pthread.h>

// tb_packet_t / tb_completion_t / the tb_client_async_* prototypes come
// from tb_client.h (included above) — the ONE definition the Go cgo and
// ctypes bindings also compile against, so layout drift is a compile
// error, not silent packet corruption.

extern "C" {

struct tb_async {
  tb_client *sessions[32];
  pthread_t threads[32];
  struct tb_async_worker_arg {
    struct tb_async *a;
    uint32_t idx;
  } worker_args[32];
  uint32_t session_count;
  tb_completion_t on_completion;
  void *ctx;
  // shared packet FIFO
  pthread_mutex_t mu;
  pthread_cond_t cv;
  tb_packet_t *head, *tail;
  bool shutdown;
};

}  // extern "C"

namespace {

constexpr uint64_t BODY_MAX = MESSAGE_SIZE_MAX - HEADER_SIZE;

// Pop a run of coalescable packets (caller holds the lock): the head
// packet, plus — for create ops — following packets of the SAME operation
// while the combined body fits one message.
tb_packet_t *pop_run(tb_async *a, uint32_t *run_len, uint64_t *body_len) {
  tb_packet_t *first = a->head;
  if (!first) return nullptr;
  uint32_t n = 1;
  uint64_t bytes = first->data_size;
  tb_packet_t *last = first;
  if (first->operation == 128 || first->operation == 129) {
    while (last->next && last->next->operation == first->operation &&
           bytes + last->next->data_size <= BODY_MAX) {
      last = last->next;
      bytes += last->data_size;
      n++;
    }
  }
  a->head = last->next;
  if (!a->head) a->tail = nullptr;
  last->next = nullptr;
  *run_len = n;
  *body_len = bytes;
  return first;
}

void complete_run(tb_async *a, tb_packet_t *run, int rc) {
  while (run) {
    tb_packet_t *next = run->next;
    run->next = nullptr;
    run->status = rc;
    a->on_completion(a->ctx, run, nullptr, 0);
    run = next;
  }
}

void *async_worker(void *arg_) {
  auto *arg = (tb_async::tb_async_worker_arg *)arg_;
  tb_async *a = arg->a;
  tb_client *c = a->sessions[arg->idx];
  auto *body = (uint8_t *)malloc(BODY_MAX);
  auto *reply = (uint8_t *)malloc(BODY_MAX);
  for (;;) {
    pthread_mutex_lock(&a->mu);
    while (!a->head && !a->shutdown) pthread_cond_wait(&a->cv, &a->mu);
    uint32_t run_len = 0;
    uint64_t body_len = 0;
    tb_packet_t *run = pop_run(a, &run_len, &body_len);
    pthread_mutex_unlock(&a->mu);
    if (!run) break;  // shutdown + drained
    if (!body || !reply) {
      complete_run(a, run, -ENOMEM);
      continue;
    }
    // coalesce bodies
    uint64_t off = 0;
    for (tb_packet_t *p = run; p; p = p->next) {
      memcpy(body + off, p->data, p->data_size);
      off += p->data_size;
    }
    uint64_t reply_len = 0;
    c->request_number += 1;
    int rc = submit_rotating(c, run->operation, c->request_number, body,
                             body_len, reply, BODY_MAX, &reply_len);
    if (rc != 0) {
      complete_run(a, run, rc);
      continue;
    }
    if (run_len == 1) {
      run->status = 0;
      a->on_completion(a->ctx, run, reply, reply_len);
      continue;
    }
    // Demux coalesced create results: sparse {u32 index, u32 result}
    // entries ordered by index; each packet consumes the entries whose
    // index falls in its event range, rebased in place.
    uint64_t entry = 0, entries = reply_len / 8;
    uint32_t ev_off = 0;
    for (tb_packet_t *p = run; p;) {
      tb_packet_t *next = p->next;
      uint32_t ev_count = p->data_size / 128;
      uint64_t start = entry;
      while (entry < entries) {
        uint32_t eidx;
        memcpy(&eidx, reply + entry * 8, 4);
        if (eidx >= ev_off + ev_count) break;
        eidx -= ev_off;
        memcpy(reply + entry * 8, &eidx, 4);
        entry++;
      }
      p->next = nullptr;
      p->status = 0;
      a->on_completion(a->ctx, p, reply + start * 8, (entry - start) * 8);
      ev_off += ev_count;
      p = next;
    }
  }
  free(body);
  free(reply);
  return nullptr;
}

}  // namespace

extern "C" {

/* Session pool + completion callback. client_id_base: 16 bytes, nonzero;
 * session i perturbs byte 0 by +i (ids must stay unique cluster-wide).
 * sessions: 1..32 concurrent sessions (each one VSR session = one request
 * in flight; the pool is the process's in-flight depth). The callback runs
 * on worker threads — it must be thread-safe. Returns 0 or -errno. */
int tb_client_async_init(tb_async **out, const char *addresses,
                         uint32_t cluster, const uint8_t client_id_base[16],
                         uint32_t sessions, tb_completion_t on_completion,
                         void *ctx) {
  if (sessions < 1 || sessions > 32 || !on_completion) return -EINVAL;
  auto *a = (tb_async *)calloc(1, sizeof(tb_async));
  if (!a) return -ENOMEM;
  a->session_count = sessions;
  a->on_completion = on_completion;
  a->ctx = ctx;
  pthread_mutex_init(&a->mu, nullptr);
  pthread_cond_init(&a->cv, nullptr);
  for (uint32_t i = 0; i < sessions; i++) {
    uint8_t cid[16];
    memcpy(cid, client_id_base, 16);
    cid[0] = (uint8_t)(cid[0] + i);
    int rc = tb_client_init(&a->sessions[i], addresses, 0, cluster, cid);
    if (rc != 0) {
      for (uint32_t j = 0; j < i; j++) tb_client_deinit(a->sessions[j]);
      free(a);
      return rc;
    }
  }
  for (uint32_t i = 0; i < sessions; i++) {
    a->worker_args[i] = {a, i};
    if (pthread_create(&a->threads[i], nullptr, async_worker,
                       &a->worker_args[i]) != 0) {
      pthread_mutex_lock(&a->mu);
      a->shutdown = true;
      pthread_cond_broadcast(&a->cv);
      pthread_mutex_unlock(&a->mu);
      for (uint32_t j = 0; j < i; j++) pthread_join(a->threads[j], nullptr);
      for (uint32_t j = 0; j < sessions; j++)
        tb_client_deinit(a->sessions[j]);
      free(a);
      return -EAGAIN;
    }
  }
  *out = a;
  return 0;
}

/* Submit a packet (caller keeps ownership of packet + data until its
 * completion callback fires). Packets are picked up FIFO by the session
 * pool; same-operation create packets may be coalesced into one request. */
int tb_client_async_submit(tb_async *a, tb_packet_t *p) {
  if (!a || !p || !p->data || p->data_size == 0 ||
      p->data_size > BODY_MAX)
    return -EINVAL;
  p->next = nullptr;
  p->status = 1; /* in flight */
  pthread_mutex_lock(&a->mu);
  if (a->shutdown) {
    pthread_mutex_unlock(&a->mu);
    return -ESHUTDOWN;
  }
  if (a->tail) a->tail->next = p;
  else a->head = p;
  a->tail = p;
  pthread_cond_signal(&a->cv);
  pthread_mutex_unlock(&a->mu);
  return 0;
}

/* Drain: workers finish every queued packet, then exit. */
void tb_client_async_deinit(tb_async *a) {
  if (!a) return;
  pthread_mutex_lock(&a->mu);
  a->shutdown = true;
  pthread_cond_broadcast(&a->cv);
  pthread_mutex_unlock(&a->mu);
  for (uint32_t i = 0; i < a->session_count; i++)
    pthread_join(a->threads[i], nullptr);
  for (uint32_t i = 0; i < a->session_count; i++)
    tb_client_deinit(a->sessions[i]);
  pthread_mutex_destroy(&a->mu);
  pthread_cond_destroy(&a->cv);
  free(a);
}

}  // extern "C"
