// Sample: two-phase transfer lifecycle through the Node client
// (mirrors clients/go/sample/main.go and the reference's node walkthrough).
//
// Run against a live cluster:
//   node clients/node/sample/main.js 127.0.0.1:3001

"use strict";

const { Client } = require("../tb_client");

function assertEqual(got, want, what) {
  if (got !== want) throw new Error(`${what}: got ${got}, want ${want}`);
}

const addresses = process.argv[2] || "127.0.0.1:3001";
const c = new Client(addresses, 0);
try {
  let errs = c.createAccounts([
    { id: 1n, ledger: 1, code: 1 },
    { id: 2n, ledger: 1, code: 1 },
  ]);
  assertEqual(errs.length, 0, "createAccounts errors");

  // pending, then partial post (two-phase; reference:
  // src/state_machine.zig:907-1014)
  errs = c.createTransfers([
    {
      id: 100n, debit_account_id: 1n, credit_account_id: 2n,
      amount: 500n, ledger: 1, code: 1, flags: 1 << 1 /* pending */,
      timeout: 3600,
    },
  ]);
  assertEqual(errs.length, 0, "pending transfer errors");
  errs = c.createTransfers([
    {
      id: 101n, pending_id: 100n, amount: 300n, ledger: 1, code: 1,
      flags: 1 << 2 /* post_pending_transfer */,
    },
  ]);
  assertEqual(errs.length, 0, "post errors");

  const accounts = c.lookupAccounts([1n, 2n]);
  assertEqual(accounts.length, 2, "accounts found");
  assertEqual(accounts[0].debits_posted, 300n, "debits_posted");
  assertEqual(accounts[1].credits_posted, 300n, "credits_posted");
  assertEqual(accounts[0].debits_pending, 0n, "pending released");

  const transfers = c.lookupTransfers([100n, 101n]);
  assertEqual(transfers.length, 2, "transfers found");
  assertEqual(transfers[1].amount, 300n, "posted amount");

  // empty batch is a no-op, not an error
  assertEqual(c.createAccounts([]).length, 0, "empty batch");

  console.log("node sample: OK");
} finally {
  c.close();
}
