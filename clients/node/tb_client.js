// Node client for the tigerbeetle_tpu cluster: an FFI wrapper over the
// tb_client C ABI (native/tb_client.{h,cc}) — the same layering as the
// reference's Node client (reference: src/clients/node wraps
// src/clients/c/tb_client.zig). Session registration, retries, checksums,
// and wire framing live in the shared native library; this file converts
// between JS objects and the 128-byte little-endian wire structs
// (field layout: clients/node/types.ts, generated from the one schema).
//
// Runtime: requires the `koffi` (or API-compatible `ffi-napi`) FFI package
// — this repo's CI image has no Node runtime, so the client is exercised
// where one exists; the exact C ABI call sequence it makes is replayed by
// tests/test_c_abi_sequence.py via ctypes everywhere (same coverage
// contract as the Go client, clients/go/tb_client.go).
//
// Usage:
//   const { Client } = require("./tb_client");
//   const c = new Client("127.0.0.1:3001", 0);
//   const errs = c.createAccounts([{ id: 1n, ledger: 1, code: 1 }]);

"use strict";

const crypto = require("crypto");

const OP_CREATE_ACCOUNTS = 128;
const OP_CREATE_TRANSFERS = 129;
const OP_LOOKUP_ACCOUNTS = 130;
const OP_LOOKUP_TRANSFERS = 131;

const EVENT_SIZE = 128;
const RESULT_SIZE = 8;
const ID_SIZE = 16;

function loadNative(libPath) {
  // koffi first (pure-prebuilt, no node-gyp), ffi-napi as fallback
  let koffi;
  try {
    koffi = require("koffi");
  } catch (_e) {
    koffi = null;
  }
  const path = libPath || `${__dirname}/../../native/libtb_native.so`;
  if (koffi) {
    const lib = koffi.load(path);
    return {
      init: lib.func(
        "int tb_client_init(_Out_ void **out, const char *addresses, int port, uint32_t cluster, const uint8_t *client_id)"
      ),
      request: lib.func(
        "int tb_client_request(void *client, uint8_t operation, const void *body, uint64_t body_len, _Out_ uint8_t *reply, uint64_t reply_cap, _Out_ uint64_t *reply_len)"
      ),
      deinit: lib.func("void tb_client_deinit(void *client)"),
      kind: "koffi",
    };
  }
  const ffi = require("ffi-napi");
  const ref = require("ref-napi");
  const voidPP = ref.refType(ref.refType(ref.types.void));
  const lib = ffi.Library(path, {
    tb_client_init: ["int", [voidPP, "string", "int", "uint32", "pointer"]],
    tb_client_request: [
      "int",
      ["pointer", "uint8", "pointer", "uint64", "pointer", "uint64", "pointer"],
    ],
    tb_client_deinit: ["void", ["pointer"]],
  });
  return { lib, ref, kind: "ffi-napi" };
}

// -- wire struct packing (layouts: tigerbeetle_tpu/types.py dtypes) --

function writeU128(buf, off, v) {
  buf.writeBigUInt64LE(BigInt(v) & 0xffffffffffffffffn, off);
  buf.writeBigUInt64LE(BigInt(v) >> 64n, off + 8);
}

function readU128(buf, off) {
  return buf.readBigUInt64LE(off) | (buf.readBigUInt64LE(off + 8) << 64n);
}

function packAccount(a) {
  const b = Buffer.alloc(EVENT_SIZE);
  writeU128(b, 0, a.id ?? 0n);
  writeU128(b, 16, a.debits_pending ?? 0n);
  writeU128(b, 32, a.debits_posted ?? 0n);
  writeU128(b, 48, a.credits_pending ?? 0n);
  writeU128(b, 64, a.credits_posted ?? 0n);
  writeU128(b, 80, a.user_data_128 ?? 0n);
  b.writeBigUInt64LE(BigInt(a.user_data_64 ?? 0), 96);
  b.writeUInt32LE(a.user_data_32 ?? 0, 104);
  b.writeUInt32LE(a.reserved ?? 0, 108);
  b.writeUInt32LE(a.ledger ?? 0, 112);
  b.writeUInt16LE(a.code ?? 0, 116);
  b.writeUInt16LE(a.flags ?? 0, 118);
  b.writeBigUInt64LE(BigInt(a.timestamp ?? 0), 120);
  return b;
}

function unpackAccount(b, off) {
  return {
    id: readU128(b, off),
    debits_pending: readU128(b, off + 16),
    debits_posted: readU128(b, off + 32),
    credits_pending: readU128(b, off + 48),
    credits_posted: readU128(b, off + 64),
    user_data_128: readU128(b, off + 80),
    user_data_64: b.readBigUInt64LE(off + 96),
    user_data_32: b.readUInt32LE(off + 104),
    reserved: b.readUInt32LE(off + 108),
    ledger: b.readUInt32LE(off + 112),
    code: b.readUInt16LE(off + 116),
    flags: b.readUInt16LE(off + 118),
    timestamp: b.readBigUInt64LE(off + 120),
  };
}

function packTransfer(t) {
  const b = Buffer.alloc(EVENT_SIZE);
  writeU128(b, 0, t.id ?? 0n);
  writeU128(b, 16, t.debit_account_id ?? 0n);
  writeU128(b, 32, t.credit_account_id ?? 0n);
  writeU128(b, 48, t.amount ?? 0n);
  writeU128(b, 64, t.pending_id ?? 0n);
  writeU128(b, 80, t.user_data_128 ?? 0n);
  b.writeBigUInt64LE(BigInt(t.user_data_64 ?? 0), 96);
  b.writeUInt32LE(t.user_data_32 ?? 0, 104);
  b.writeUInt32LE(t.timeout ?? 0, 108);
  b.writeUInt32LE(t.ledger ?? 0, 112);
  b.writeUInt16LE(t.code ?? 0, 116);
  b.writeUInt16LE(t.flags ?? 0, 118);
  b.writeBigUInt64LE(BigInt(t.timestamp ?? 0), 120);
  return b;
}

function unpackTransfer(b, off) {
  return {
    id: readU128(b, off),
    debit_account_id: readU128(b, off + 16),
    credit_account_id: readU128(b, off + 32),
    amount: readU128(b, off + 48),
    pending_id: readU128(b, off + 64),
    user_data_128: readU128(b, off + 80),
    user_data_64: b.readBigUInt64LE(off + 96),
    user_data_32: b.readUInt32LE(off + 104),
    timeout: b.readUInt32LE(off + 108),
    ledger: b.readUInt32LE(off + 112),
    code: b.readUInt16LE(off + 116),
    flags: b.readUInt16LE(off + 118),
    timestamp: b.readBigUInt64LE(off + 120),
  };
}

function unpackResults(reply) {
  const out = [];
  for (let off = 0; off + RESULT_SIZE <= reply.length; off += RESULT_SIZE) {
    out.push({
      index: reply.readUInt32LE(off),
      result: reply.readUInt32LE(off + 4),
    });
  }
  return out;
}

class Client {
  // addresses: "host:port[,host:port...]"; cluster id must match format.
  constructor(addresses, cluster, libPath) {
    this._native = loadNative(libPath);
    const id = crypto.randomBytes(16);
    id[0] |= 1; // nonzero
    if (this._native.kind === "koffi") {
      const out = [null];
      const rc = this._native.init(out, addresses, 0, cluster >>> 0, id);
      if (rc !== 0) throw new Error(`tb_client_init: errno ${-rc}`);
      this._handle = out[0];
    } else {
      const { lib, ref } = this._native;
      const outPtr = ref.alloc("pointer");
      const rc = lib.tb_client_init(outPtr, addresses, 0, cluster >>> 0, id);
      if (rc !== 0) throw new Error(`tb_client_init: errno ${-rc}`);
      this._handle = outPtr.deref();
    }
  }

  close() {
    if (!this._handle) return;
    if (this._native.kind === "koffi") this._native.deinit(this._handle);
    else this._native.lib.tb_client_deinit(this._handle);
    this._handle = null;
  }

  _request(op, body, replyCap) {
    if (replyCap === 0) return Buffer.alloc(0); // empty batch: no-op
    const reply = Buffer.alloc(replyCap);
    if (this._native.kind === "koffi") {
      const lenOut = [0n];
      const rc = this._native.request(
        this._handle, op, body, BigInt(body.length), reply,
        BigInt(replyCap), lenOut
      );
      if (rc !== 0) throw new Error(`tb_client_request: errno ${-rc}`);
      return reply.subarray(0, Number(lenOut[0]));
    }
    const { lib, ref } = this._native;
    const lenPtr = ref.alloc("uint64");
    const rc = lib.tb_client_request(
      this._handle, op, body, body.length, reply, replyCap, lenPtr
    );
    if (rc !== 0) throw new Error(`tb_client_request: errno ${-rc}`);
    return reply.subarray(0, Number(lenPtr.deref()));
  }

  // Sparse non-ok {index, result} pairs; empty array = all applied.
  createAccounts(accounts) {
    const body = Buffer.concat(accounts.map(packAccount));
    return unpackResults(
      this._request(OP_CREATE_ACCOUNTS, body, accounts.length * RESULT_SIZE)
    );
  }

  createTransfers(transfers) {
    const body = Buffer.concat(transfers.map(packTransfer));
    return unpackResults(
      this._request(OP_CREATE_TRANSFERS, body, transfers.length * RESULT_SIZE)
    );
  }

  // Found rows in request order (missing ids skipped).
  lookupAccounts(ids) {
    const body = Buffer.alloc(ids.length * ID_SIZE);
    ids.forEach((x, i) => writeU128(body, i * ID_SIZE, x));
    const reply = this._request(
      OP_LOOKUP_ACCOUNTS, body, ids.length * EVENT_SIZE
    );
    const out = [];
    for (let off = 0; off + EVENT_SIZE <= reply.length; off += EVENT_SIZE)
      out.push(unpackAccount(reply, off));
    return out;
  }

  lookupTransfers(ids) {
    const body = Buffer.alloc(ids.length * ID_SIZE);
    ids.forEach((x, i) => writeU128(body, i * ID_SIZE, x));
    const reply = this._request(
      OP_LOOKUP_TRANSFERS, body, ids.length * EVENT_SIZE
    );
    const out = [];
    for (let off = 0; off + EVENT_SIZE <= reply.length; off += EVENT_SIZE)
      out.push(unpackTransfer(reply, off));
    return out;
  }
}

// -- async packet API (the reference's packet/completion model) ----------
//
// A pool of N sessions; submits resolve as Promises. Node's equivalent of
// the C tb_client_async session pool (native/tb_client.h): with koffi the
// blocking tb_client_request is dispatched on libuv worker threads via
// `.async`, so N requests ride the wire concurrently while the event loop
// stays free — the same in-flight depth the C pool's pthreads provide.
// (The C-level tb_client_async_* interface with its completion callback is
// exercised everywhere by tests/test_async_client.py via ctypes.)

class AsyncClient {
  constructor(addresses, cluster, sessions = 4, libPath) {
    this._sessions = [];
    this._free = [];
    this._waiters = [];
    for (let i = 0; i < sessions; i++) {
      const c = new Client(addresses, cluster, libPath);
      if (c._native.kind !== "koffi") {
        // ffi-napi also exposes .async on bound functions; normalize
        c._requestAsync = (op, body, cap) =>
          new Promise((resolve, reject) => {
            if (cap === 0) return resolve(Buffer.alloc(0)); // empty batch
            const reply = Buffer.alloc(cap);
            const lenPtr = c._native.ref.alloc("uint64");
            c._native.lib.tb_client_request.async(
              c._handle, op, body, body.length, reply, cap, lenPtr,
              (err, rc) => {
                if (err || rc !== 0) reject(err || new Error(`errno ${-rc}`));
                else resolve(reply.subarray(0, Number(lenPtr.deref())));
              }
            );
          });
      } else {
        c._requestAsync = (op, body, cap) =>
          new Promise((resolve, reject) => {
            if (cap === 0) return resolve(Buffer.alloc(0)); // empty batch
            const reply = Buffer.alloc(cap);
            const lenOut = [0n];
            c._native.request.async(
              c._handle, op, body, BigInt(body.length), reply,
              BigInt(cap), lenOut,
              (err, rc) => {
                if (err || rc !== 0) reject(err || new Error(`errno ${-rc}`));
                else resolve(reply.subarray(0, Number(lenOut[0])));
              }
            );
          });
      }
      this._sessions.push(c);
      this._free.push(c);
    }
  }

  async _withSession(fn) {
    if (this._closing) throw new Error("async client closed");
    const c = this._free.length
      ? this._free.pop()
      : await new Promise((resolve, reject) =>
          this._waiters.push({ resolve, reject })
        );
    try {
      return await fn(c);
    } finally {
      const w = this._waiters.shift();
      if (w) w.resolve(c);
      else {
        this._free.push(c);
        if (this._closing && this._onIdle &&
            this._free.length === this._sessions.length)
          this._onIdle();
      }
    }
  }

  createAccounts(accounts) {
    const body = Buffer.concat(accounts.map(packAccount));
    return this._withSession((c) =>
      c._requestAsync(OP_CREATE_ACCOUNTS, body, accounts.length * RESULT_SIZE)
    ).then(unpackResults);
  }

  createTransfers(transfers) {
    const body = Buffer.concat(transfers.map(packTransfer));
    return this._withSession((c) =>
      c._requestAsync(OP_CREATE_TRANSFERS, body, transfers.length * RESULT_SIZE)
    ).then(unpackResults);
  }

  lookupAccounts(ids) {
    const body = Buffer.alloc(ids.length * ID_SIZE);
    ids.forEach((x, i) => writeU128(body, i * ID_SIZE, x));
    return this._withSession((c) =>
      c._requestAsync(OP_LOOKUP_ACCOUNTS, body, ids.length * EVENT_SIZE)
    ).then((reply) => {
      const out = [];
      for (let off = 0; off + EVENT_SIZE <= reply.length; off += EVENT_SIZE)
        out.push(unpackAccount(reply, off));
      return out;
    });
  }

  lookupTransfers(ids) {
    const body = Buffer.alloc(ids.length * ID_SIZE);
    ids.forEach((x, i) => writeU128(body, i * ID_SIZE, x));
    return this._withSession((c) =>
      c._requestAsync(OP_LOOKUP_TRANSFERS, body, ids.length * EVENT_SIZE)
    ).then((reply) => {
      const out = [];
      for (let off = 0; off + EVENT_SIZE <= reply.length; off += EVENT_SIZE)
        out.push(unpackTransfer(reply, off));
      return out;
    });
  }

  // Waits for in-flight requests to finish (a deinit while a libuv worker
  // is inside tb_client_request would be a use-after-free), rejects parked
  // waiters, then deinits every session.
  async close() {
    this._closing = true;
    for (const w of this._waiters.splice(0))
      w.reject(new Error("async client closed"));
    if (this._free.length !== this._sessions.length)
      await new Promise((resolve) => (this._onIdle = resolve));
    for (const c of this._sessions) c.close();
    this._sessions = [];
    this._free = [];
  }
}

module.exports = {
  Client,
  AsyncClient,
  packAccount,
  packTransfer,
  unpackAccount,
  unpackTransfer,
  unpackResults,
};
