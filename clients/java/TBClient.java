// Java client for the tigerbeetle_tpu cluster: an FFI wrapper over the
// tb_client C ABI (native/tb_client.{h,cc}) — the same layering as the
// reference's Java client (reference: src/clients/java wraps
// src/clients/c/tb_client.zig through JNI glue). Session registration,
// retries, checksums, and wire framing live in the shared native library;
// this file converts between TBTypes objects and the 128-byte
// little-endian wire structs (field layout: TBTypes.java, generated from
// the one schema by scripts/bindgen.py).
//
// Runtime: java.lang.foreign (the FFM API, final since JDK 22) — no JNI
// glue, no extra jar. This repo's CI image has no JVM, so the client is
// exercised where one exists; the exact C ABI call sequence it makes
// (init signature, reply-capacity math, the empty-batch early return,
// deinit) is replayed by tests/test_c_abi_sequence.py via ctypes
// everywhere — the same coverage contract as the Go and Node clients.
//
// Usage:
//   var c = new TBClient("127.0.0.1:3001", 0);
//   var errs = c.createAccounts(accounts);   // sparse non-ok results
//   c.close();

package com.tigerbeetle;

import java.lang.foreign.Arena;
import java.lang.foreign.FunctionDescriptor;
import java.lang.foreign.Linker;
import java.lang.foreign.MemorySegment;
import java.lang.foreign.SymbolLookup;
import java.lang.foreign.ValueLayout;
import java.lang.invoke.MethodHandle;
import java.nio.ByteBuffer;
import java.nio.ByteOrder;
import java.nio.file.Path;
import java.security.SecureRandom;
import java.util.ArrayList;
import java.util.List;
import java.util.concurrent.CompletableFuture;
import java.util.concurrent.ExecutorService;
import java.util.concurrent.Executors;
import java.util.concurrent.Semaphore;

public final class TBClient implements AutoCloseable {
    public static final int OP_CREATE_ACCOUNTS = 128;
    public static final int OP_CREATE_TRANSFERS = 129;
    public static final int OP_LOOKUP_ACCOUNTS = 130;
    public static final int OP_LOOKUP_TRANSFERS = 131;

    public static final int EVENT_SIZE = 128;
    public static final int RESULT_SIZE = 8;
    public static final int ID_SIZE = 16;

    private static final Linker LINKER = Linker.nativeLinker();
    private static MethodHandle hInit;
    private static MethodHandle hRequest;
    private static MethodHandle hDeinit;

    private final Arena arena = Arena.ofShared();
    private MemorySegment handle; // tb_client_t*

    private static synchronized void loadNative(String libPath) {
        if (hInit != null) return;
        String path = libPath != null ? libPath
            : Path.of(System.getProperty("tb.native",
                "../../native/libtb_native.so")).toString();
        SymbolLookup lib = SymbolLookup.libraryLookup(path, Arena.global());
        // int tb_client_init(tb_client_t **out, const char *addresses,
        //                    int port, uint32_t cluster,
        //                    const uint8_t client_id[16])
        hInit = LINKER.downcallHandle(
            lib.find("tb_client_init").orElseThrow(),
            FunctionDescriptor.of(ValueLayout.JAVA_INT,
                ValueLayout.ADDRESS, ValueLayout.ADDRESS,
                ValueLayout.JAVA_INT, ValueLayout.JAVA_INT,
                ValueLayout.ADDRESS));
        // int tb_client_request(tb_client_t *c, uint8_t op, const void
        //   *body, uint64_t body_len, void *reply, uint64_t reply_cap,
        //   uint64_t *reply_len)
        hRequest = LINKER.downcallHandle(
            lib.find("tb_client_request").orElseThrow(),
            FunctionDescriptor.of(ValueLayout.JAVA_INT,
                ValueLayout.ADDRESS, ValueLayout.JAVA_BYTE,
                ValueLayout.ADDRESS, ValueLayout.JAVA_LONG,
                ValueLayout.ADDRESS, ValueLayout.JAVA_LONG,
                ValueLayout.ADDRESS));
        // void tb_client_deinit(tb_client_t *c)
        hDeinit = LINKER.downcallHandle(
            lib.find("tb_client_deinit").orElseThrow(),
            FunctionDescriptor.ofVoid(ValueLayout.ADDRESS));
    }

    /** addresses: "host:port[,host:port...]"; cluster id must match the
     *  data file's. The client id is 16 random nonzero bytes. */
    public TBClient(String addresses, int cluster) {
        this(addresses, cluster, null);
    }

    public TBClient(String addresses, int cluster, String libPath) {
        loadNative(libPath);
        byte[] id = new byte[16];
        new SecureRandom().nextBytes(id);
        id[0] |= 1; // nonzero
        MemorySegment out = arena.allocate(ValueLayout.ADDRESS);
        MemorySegment addr = arena.allocateFrom(addresses);
        MemorySegment cid = arena.allocate(16);
        MemorySegment.copy(id, 0, cid, ValueLayout.JAVA_BYTE, 0, 16);
        try {
            int rc = (int) hInit.invokeExact(out, addr, 0, cluster, cid);
            if (rc != 0)
                throw new RuntimeException("tb_client_init: errno " + (-rc));
        } catch (RuntimeException e) {
            throw e;
        } catch (Throwable t) {
            throw new RuntimeException(t);
        }
        handle = out.get(ValueLayout.ADDRESS, 0);
    }

    @Override
    public synchronized void close() {
        if (handle == null) return;
        try {
            hDeinit.invokeExact(handle);
        } catch (Throwable t) {
            throw new RuntimeException(t);
        }
        handle = null;
        arena.close();
    }

    private byte[] request(int op, byte[] body, int replyCap) {
        // the Go/Node wrappers' guard: zero reply capacity -> no call
        if (replyCap == 0) return new byte[0];
        try (Arena call = Arena.ofConfined()) {
            MemorySegment bodySeg = body.length == 0
                ? MemorySegment.NULL : call.allocate(body.length);
            if (body.length != 0)
                MemorySegment.copy(body, 0, bodySeg, ValueLayout.JAVA_BYTE,
                    0, body.length);
            MemorySegment reply = call.allocate(replyCap);
            MemorySegment len = call.allocate(ValueLayout.JAVA_LONG);
            int rc;
            try {
                rc = (int) hRequest.invokeExact(handle, (byte) op, bodySeg,
                    (long) body.length, reply, (long) replyCap, len);
            } catch (Throwable t) {
                throw new RuntimeException(t);
            }
            if (rc != 0)
                throw new RuntimeException(
                    "tb_client_request: errno " + (-rc));
            int n = (int) len.get(ValueLayout.JAVA_LONG, 0);
            byte[] outBytes = new byte[n];
            MemorySegment.copy(reply, ValueLayout.JAVA_BYTE, 0, outBytes,
                0, n);
            return outBytes;
        }
    }

    // -- wire struct packing (layouts: tigerbeetle_tpu/types.py dtypes) --

    private static ByteBuffer wire(int n) {
        return ByteBuffer.allocate(n).order(ByteOrder.LITTLE_ENDIAN);
    }

    private static void putU128(ByteBuffer b, byte[] v) {
        if (v == null) { b.putLong(0).putLong(0); return; }
        if (v.length != ID_SIZE)
            throw new IllegalArgumentException("u128 must be 16 bytes LE");
        b.put(v);
    }

    private static byte[] getU128(ByteBuffer b) {
        byte[] v = new byte[ID_SIZE];
        b.get(v);
        return v;
    }

    /** Little-endian u128 from a non-negative long (convenience). */
    public static byte[] u128(long lo) {
        ByteBuffer b = wire(ID_SIZE);
        b.putLong(lo).putLong(0);
        return b.array();
    }

    static byte[] packAccount(TBTypes.Account a) {
        ByteBuffer b = wire(EVENT_SIZE);
        putU128(b, a.id);
        putU128(b, a.debits_pending);
        putU128(b, a.debits_posted);
        putU128(b, a.credits_pending);
        putU128(b, a.credits_posted);
        putU128(b, a.user_data_128);
        b.putLong(a.user_data_64).putInt(a.user_data_32).putInt(a.reserved)
            .putInt(a.ledger).putShort(a.code).putShort(a.flags)
            .putLong(a.timestamp);
        return b.array();
    }

    static TBTypes.Account unpackAccount(ByteBuffer b) {
        TBTypes.Account a = new TBTypes.Account();
        a.id = getU128(b);
        a.debits_pending = getU128(b);
        a.debits_posted = getU128(b);
        a.credits_pending = getU128(b);
        a.credits_posted = getU128(b);
        a.user_data_128 = getU128(b);
        a.user_data_64 = b.getLong();
        a.user_data_32 = b.getInt();
        a.reserved = b.getInt();
        a.ledger = b.getInt();
        a.code = b.getShort();
        a.flags = b.getShort();
        a.timestamp = b.getLong();
        return a;
    }

    static byte[] packTransfer(TBTypes.Transfer t) {
        ByteBuffer b = wire(EVENT_SIZE);
        putU128(b, t.id);
        putU128(b, t.debit_account_id);
        putU128(b, t.credit_account_id);
        putU128(b, t.amount);
        putU128(b, t.pending_id);
        putU128(b, t.user_data_128);
        b.putLong(t.user_data_64).putInt(t.user_data_32).putInt(t.timeout)
            .putInt(t.ledger).putShort(t.code).putShort(t.flags)
            .putLong(t.timestamp);
        return b.array();
    }

    static TBTypes.Transfer unpackTransfer(ByteBuffer b) {
        TBTypes.Transfer t = new TBTypes.Transfer();
        t.id = getU128(b);
        t.debit_account_id = getU128(b);
        t.credit_account_id = getU128(b);
        t.amount = getU128(b);
        t.pending_id = getU128(b);
        t.user_data_128 = getU128(b);
        t.user_data_64 = b.getLong();
        t.user_data_32 = b.getInt();
        t.timeout = b.getInt();
        t.ledger = b.getInt();
        t.code = b.getShort();
        t.flags = b.getShort();
        t.timestamp = b.getLong();
        return t;
    }

    private static List<TBTypes.CreateAccountsResult> unpackResults(
            byte[] reply) {
        ByteBuffer b = ByteBuffer.wrap(reply)
            .order(ByteOrder.LITTLE_ENDIAN);
        List<TBTypes.CreateAccountsResult> out = new ArrayList<>();
        while (b.remaining() >= RESULT_SIZE) {
            TBTypes.CreateAccountsResult r =
                new TBTypes.CreateAccountsResult();
            r.index = b.getInt();
            r.result = b.getInt();
            out.add(r);
        }
        return out;
    }

    // -- the five operations (sparse non-ok results; found rows in
    //    request order with missing ids skipped) --

    public List<TBTypes.CreateAccountsResult> createAccounts(
            List<TBTypes.Account> accounts) {
        ByteBuffer body = wire(accounts.size() * EVENT_SIZE);
        for (TBTypes.Account a : accounts) body.put(packAccount(a));
        return unpackResults(request(OP_CREATE_ACCOUNTS, body.array(),
            accounts.size() * RESULT_SIZE));
    }

    public List<TBTypes.CreateAccountsResult> createTransfers(
            List<TBTypes.Transfer> transfers) {
        ByteBuffer body = wire(transfers.size() * EVENT_SIZE);
        for (TBTypes.Transfer t : transfers) body.put(packTransfer(t));
        return unpackResults(request(OP_CREATE_TRANSFERS, body.array(),
            transfers.size() * RESULT_SIZE));
    }

    public List<TBTypes.Account> lookupAccounts(List<byte[]> ids) {
        ByteBuffer body = wire(ids.size() * ID_SIZE);
        for (byte[] id : ids) putU128(body, id);
        byte[] reply = request(OP_LOOKUP_ACCOUNTS, body.array(),
            ids.size() * EVENT_SIZE);
        ByteBuffer b = ByteBuffer.wrap(reply)
            .order(ByteOrder.LITTLE_ENDIAN);
        List<TBTypes.Account> out = new ArrayList<>();
        while (b.remaining() >= EVENT_SIZE) out.add(unpackAccount(b));
        return out;
    }

    public List<TBTypes.Transfer> lookupTransfers(List<byte[]> ids) {
        ByteBuffer body = wire(ids.size() * ID_SIZE);
        for (byte[] id : ids) putU128(body, id);
        byte[] reply = request(OP_LOOKUP_TRANSFERS, body.array(),
            ids.size() * EVENT_SIZE);
        ByteBuffer b = ByteBuffer.wrap(reply)
            .order(ByteOrder.LITTLE_ENDIAN);
        List<TBTypes.Transfer> out = new ArrayList<>();
        while (b.remaining() >= EVENT_SIZE) out.add(unpackTransfer(b));
        return out;
    }

    // -- async session pool (the reference's packet/completion model;
    //    same shape as the Go goroutine pool and the Node libuv pool:
    //    N sessions, each blocking request on a pool thread, submits
    //    resolve as CompletableFutures) --

    public static final class AsyncClient implements AutoCloseable {
        private final List<TBClient> sessions = new ArrayList<>();
        private final Semaphore free;
        private final ExecutorService pool;
        private volatile boolean closing;

        public AsyncClient(String addresses, int cluster, int nSessions) {
            if (nSessions < 1 || nSessions > 32)
                throw new IllegalArgumentException("1..32 sessions");
            for (int i = 0; i < nSessions; i++)
                sessions.add(new TBClient(addresses, cluster));
            free = new Semaphore(nSessions, true);
            pool = Executors.newFixedThreadPool(nSessions);
        }

        private <T> CompletableFuture<T> withSession(
                java.util.function.Function<TBClient, T> fn) {
            if (closing)
                return CompletableFuture.failedFuture(
                    new IllegalStateException("async client closed"));
            CompletableFuture<T> fut = new CompletableFuture<>();
            try {
                submitTask(fut, fn);
            } catch (java.util.concurrent.RejectedExecutionException e) {
                // close() raced us: fail the future instead of throwing
                fut.completeExceptionally(
                    new IllegalStateException("async client closed", e));
            }
            return fut;
        }

        private <T> void submitTask(CompletableFuture<T> fut,
                java.util.function.Function<TBClient, T> fn) {
            pool.submit(() -> {
                try {
                    free.acquire();
                    TBClient c;
                    synchronized (sessions) {
                        c = sessions.remove(sessions.size() - 1);
                    }
                    try {
                        fut.complete(fn.apply(c));
                    } finally {
                        synchronized (sessions) {
                            sessions.add(c);
                        }
                        free.release();
                    }
                } catch (Throwable t) {
                    fut.completeExceptionally(t);
                }
            });
        }

        public CompletableFuture<List<TBTypes.CreateAccountsResult>>
                createAccounts(List<TBTypes.Account> accounts) {
            return withSession(c -> c.createAccounts(accounts));
        }

        public CompletableFuture<List<TBTypes.CreateAccountsResult>>
                createTransfers(List<TBTypes.Transfer> transfers) {
            return withSession(c -> c.createTransfers(transfers));
        }

        public CompletableFuture<List<TBTypes.Account>> lookupAccounts(
                List<byte[]> ids) {
            return withSession(c -> c.lookupAccounts(ids));
        }

        public CompletableFuture<List<TBTypes.Transfer>> lookupTransfers(
                List<byte[]> ids) {
            return withSession(c -> c.lookupTransfers(ids));
        }

        /** Waits for in-flight requests (a deinit mid-request would be a
         *  use-after-free), then deinits every session. */
        @Override
        public void close() {
            closing = true;
            pool.shutdown();
            try {
                pool.awaitTermination(60,
                    java.util.concurrent.TimeUnit.SECONDS);
            } catch (InterruptedException e) {
                Thread.currentThread().interrupt();
            }
            synchronized (sessions) {
                for (TBClient c : sessions) c.close();
                sessions.clear();
            }
        }
    }
}
