// Sample: two-phase transfer lifecycle through the Java client
// (mirrors clients/go/sample/main.go and clients/node/sample/main.js).
//
// Run against a live cluster (JDK 22+, FFM is final):
//   javac -d build clients/java/TBTypes.java clients/java/TBClient.java \
//         clients/java/sample/Sample.java
//   java -cp build --enable-native-access=ALL-UNNAMED \
//        -Dtb.native=native/libtb_native.so \
//        com.tigerbeetle.Sample 127.0.0.1:3001

package com.tigerbeetle;

import java.util.List;

public final class Sample {
    static void check(boolean ok, String what) {
        if (!ok) throw new AssertionError(what);
    }

    static long u128lo(byte[] v) {
        long lo = 0;
        for (int i = 7; i >= 0; i--) lo = (lo << 8) | (v[i] & 0xffL);
        return lo;
    }

    public static void main(String[] args) {
        String addresses = args.length > 0 ? args[0] : "127.0.0.1:3001";
        try (TBClient c = new TBClient(addresses, 0)) {
            TBTypes.Account a1 = new TBTypes.Account();
            a1.id = TBClient.u128(1);
            a1.ledger = 1;
            a1.code = 1;
            TBTypes.Account a2 = new TBTypes.Account();
            a2.id = TBClient.u128(2);
            a2.ledger = 1;
            a2.code = 1;
            check(c.createAccounts(List.of(a1, a2)).isEmpty(),
                "createAccounts errors");

            // pending, then partial post (two-phase; reference:
            // src/state_machine.zig:907-1014)
            TBTypes.Transfer pend = new TBTypes.Transfer();
            pend.id = TBClient.u128(100);
            pend.debit_account_id = TBClient.u128(1);
            pend.credit_account_id = TBClient.u128(2);
            pend.amount = TBClient.u128(500);
            pend.ledger = 1;
            pend.code = 1;
            pend.flags = 1 << 1; // pending
            pend.timeout = 3600;
            check(c.createTransfers(List.of(pend)).isEmpty(),
                "pending transfer errors");

            TBTypes.Transfer post = new TBTypes.Transfer();
            post.id = TBClient.u128(101);
            post.pending_id = TBClient.u128(100);
            post.amount = TBClient.u128(300);
            post.ledger = 1;
            post.code = 1;
            post.flags = 1 << 2; // post_pending_transfer
            check(c.createTransfers(List.of(post)).isEmpty(), "post errors");

            List<TBTypes.Account> accounts = c.lookupAccounts(
                List.of(TBClient.u128(1), TBClient.u128(2)));
            check(accounts.size() == 2, "accounts found");
            check(u128lo(accounts.get(0).debits_posted) == 300,
                "debits_posted");
            check(u128lo(accounts.get(1).credits_posted) == 300,
                "credits_posted");
            check(u128lo(accounts.get(0).debits_pending) == 0,
                "pending released");

            List<TBTypes.Transfer> transfers = c.lookupTransfers(
                List.of(TBClient.u128(100), TBClient.u128(101)));
            check(transfers.size() == 2, "transfers found");
            check(u128lo(transfers.get(1).amount) == 300, "posted amount");

            // empty batch is a no-op, not an error
            check(c.createAccounts(List.of()).isEmpty(), "empty batch");

            System.out.println("java sample: OK");
        }
    }
}
