// Two-phase transfer sample for the Go client (the reference ships the
// same walkthrough per language, reference: src/clients/go samples):
// create accounts, move funds, hold a pending amount, post part of it,
// and verify the balances via lookups. Exits 0 on success.
//
// Usage: sample <addresses>   (e.g. "127.0.0.1:3001")
package main

import (
	"encoding/binary"
	"fmt"
	"os"
	"unsafe"

	tb "tigerbeetle_tpu/clients/go"
)

const (
	flagPending = 1 << 1
	flagPost    = 1 << 2
)

func u128lo(v tb.Uint128) uint64 { return binary.LittleEndian.Uint64(v[:8]) }

func main() {
	if unsafe.Sizeof(tb.Account{}) != 128 || unsafe.Sizeof(tb.Transfer{}) != 128 {
		panic("wire struct layout mismatch")
	}
	addresses := "127.0.0.1:3001"
	if len(os.Args) > 1 {
		addresses = os.Args[1]
	}
	client, err := tb.NewClient(addresses, 0)
	if err != nil {
		panic(err)
	}
	defer client.Close()

	accounts := []tb.Account{
		{Id: tb.U128(1, 0), Ledger: 1, Code: 10},
		{Id: tb.U128(2, 0), Ledger: 1, Code: 10},
	}
	if res, err := client.CreateAccounts(accounts); err != nil || len(res) != 0 {
		panic(fmt.Sprint("create_accounts: ", res, err))
	}

	transfers := []tb.Transfer{
		// simple transfer: 1 -> 2, amount 100
		{Id: tb.U128(100, 0), DebitAccountId: tb.U128(1, 0),
			CreditAccountId: tb.U128(2, 0), Amount: tb.U128(100, 0),
			Ledger: 1, Code: 1},
		// two-phase: hold 50 pending...
		{Id: tb.U128(101, 0), DebitAccountId: tb.U128(1, 0),
			CreditAccountId: tb.U128(2, 0), Amount: tb.U128(50, 0),
			Ledger: 1, Code: 1, Flags: flagPending},
	}
	if res, err := client.CreateTransfers(transfers); err != nil || len(res) != 0 {
		panic(fmt.Sprint("create_transfers: ", res, err))
	}
	// ...then post 30 of the 50
	post := []tb.Transfer{
		{Id: tb.U128(102, 0), PendingId: tb.U128(101, 0),
			Amount: tb.U128(30, 0), Flags: flagPost},
	}
	if res, err := client.CreateTransfers(post); err != nil || len(res) != 0 {
		panic(fmt.Sprint("post_pending: ", res, err))
	}

	got, err := client.LookupAccounts([]tb.Uint128{tb.U128(1, 0), tb.U128(2, 0)})
	if err != nil || len(got) != 2 {
		panic(fmt.Sprint("lookup_accounts: ", err))
	}
	if u128lo(got[0].DebitsPosted) != 130 || u128lo(got[1].CreditsPosted) != 130 {
		panic(fmt.Sprintf("balance mismatch: dr=%d cr=%d",
			u128lo(got[0].DebitsPosted), u128lo(got[1].CreditsPosted)))
	}
	if u128lo(got[0].DebitsPending) != 0 {
		panic("pending not released after post")
	}
	xfers, err := client.LookupTransfers([]tb.Uint128{tb.U128(102, 0)})
	if err != nil || len(xfers) != 1 || u128lo(xfers[0].Amount) != 30 {
		panic("lookup_transfers mismatch")
	}
	fmt.Println("go sample ok: two-phase balances verified")
}
