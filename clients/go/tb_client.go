// Go client for the tigerbeetle_tpu cluster: a cgo wrapper over the
// tb_client C ABI (native/tb_client.{h,cc}), the same layering as the
// reference's Go client (reference: src/clients/go/tb_client.go wraps
// src/clients/c/tb_client.zig) — session registration, retries, checksums,
// and wire framing live in the shared native library; this file converts
// between Go types and the 128-byte wire structs.
//
// Build: the repo's CI image has no Go toolchain, so this package is
// exercised by tests/test_go_client.py ONLY where `go` is available
// (skipped otherwise). Build against the native library with:
//
//	CGO_CFLAGS="-I${REPO}/native" \
//	CGO_LDFLAGS="-L${REPO}/native -ltb_native -Wl,-rpath,${REPO}/native" \
//	go build ./...
package tigerbeetle

/*
#cgo CFLAGS: -I.
#include <stdint.h>
#include <stdlib.h>
#include "tb_client.h"
*/
import "C"

import (
	"crypto/rand"
	"encoding/binary"
	"errors"
	"fmt"
	"sync"
	"unsafe"
)

const (
	opCreateAccounts  = 128
	opCreateTransfers = 129
	opLookupAccounts  = 130
	opLookupTransfers = 131

	eventSize  = 128
	resultSize = 8
	idSize     = 16
)

// U128 builds a little-endian Uint128 from lo/hi words.
func U128(lo, hi uint64) Uint128 {
	var out Uint128
	binary.LittleEndian.PutUint64(out[:8], lo)
	binary.LittleEndian.PutUint64(out[8:], hi)
	return out
}

// Client is one session against the cluster. One in-flight request at a
// time (the native layer enforces the session protocol).
type Client struct {
	handle *C.tb_client_t
}

// NewClient connects and registers a session. addresses:
// "host:port[,host:port...]".
func NewClient(addresses string, cluster uint32) (*Client, error) {
	var id [16]byte
	if _, err := rand.Read(id[:]); err != nil {
		return nil, err
	}
	id[0] |= 1 // nonzero
	caddr := C.CString(addresses)
	defer C.free(unsafe.Pointer(caddr))
	var handle *C.tb_client_t
	rc := C.tb_client_init(
		&handle, caddr, 0, C.uint32_t(cluster),
		(*C.uint8_t)(unsafe.Pointer(&id[0])),
	)
	if rc != 0 {
		return nil, fmt.Errorf("tb_client_init: errno %d", -int(rc))
	}
	return &Client{handle: handle}, nil
}

func (c *Client) Close() {
	if c.handle != nil {
		C.tb_client_deinit(c.handle)
		c.handle = nil
	}
}

func (c *Client) request(op uint8, body []byte, replyCap int) ([]byte, error) {
	if replyCap == 0 {
		// Empty batch: nothing to submit, and &reply[0] below would panic
		// on a zero-length slice.
		return nil, nil
	}
	reply := make([]byte, replyCap)
	var replyLen C.uint64_t
	var bodyPtr unsafe.Pointer
	if len(body) > 0 {
		bodyPtr = unsafe.Pointer(&body[0])
	}
	rc := C.tb_client_request(
		c.handle, C.uint8_t(op), bodyPtr, C.uint64_t(len(body)),
		unsafe.Pointer(&reply[0]), C.uint64_t(replyCap), &replyLen,
	)
	if rc != 0 {
		return nil, errors.New("tb_client_request failed")
	}
	return reply[:int(replyLen)], nil
}

// CreateAccounts submits a batch; returns sparse (index, result) pairs for
// non-ok events (empty = all applied).
func (c *Client) CreateAccounts(accounts []Account) ([]CreateAccountsResult, error) {
	body := make([]byte, 0, len(accounts)*eventSize)
	for i := range accounts {
		body = append(body, structBytes(unsafe.Pointer(&accounts[i]))...)
	}
	reply, err := c.request(opCreateAccounts, body, len(accounts)*resultSize)
	if err != nil {
		return nil, err
	}
	out := make([]CreateAccountsResult, len(reply)/resultSize)
	for i := range out {
		out[i].Index = binary.LittleEndian.Uint32(reply[i*resultSize:])
		out[i].Result = binary.LittleEndian.Uint32(reply[i*resultSize+4:])
	}
	return out, nil
}

// CreateTransfers submits a batch; returns sparse (index, result) pairs.
func (c *Client) CreateTransfers(transfers []Transfer) ([]CreateTransfersResult, error) {
	body := make([]byte, 0, len(transfers)*eventSize)
	for i := range transfers {
		body = append(body, structBytes(unsafe.Pointer(&transfers[i]))...)
	}
	reply, err := c.request(opCreateTransfers, body, len(transfers)*resultSize)
	if err != nil {
		return nil, err
	}
	out := make([]CreateTransfersResult, len(reply)/resultSize)
	for i := range out {
		out[i].Index = binary.LittleEndian.Uint32(reply[i*resultSize:])
		out[i].Result = binary.LittleEndian.Uint32(reply[i*resultSize+4:])
	}
	return out, nil
}

// LookupAccounts returns the found accounts in request order (missing ids
// skipped).
func (c *Client) LookupAccounts(ids []Uint128) ([]Account, error) {
	body := make([]byte, 0, len(ids)*idSize)
	for i := range ids {
		body = append(body, ids[i][:]...)
	}
	reply, err := c.request(opLookupAccounts, body, len(ids)*eventSize)
	if err != nil {
		return nil, err
	}
	out := make([]Account, len(reply)/eventSize)
	for i := range out {
		copy(structSlice(unsafe.Pointer(&out[i])), reply[i*eventSize:(i+1)*eventSize])
	}
	return out, nil
}

// LookupTransfers returns the found transfers in request order.
func (c *Client) LookupTransfers(ids []Uint128) ([]Transfer, error) {
	body := make([]byte, 0, len(ids)*idSize)
	for i := range ids {
		body = append(body, ids[i][:]...)
	}
	reply, err := c.request(opLookupTransfers, body, len(ids)*eventSize)
	if err != nil {
		return nil, err
	}
	out := make([]Transfer, len(reply)/eventSize)
	for i := range out {
		copy(structSlice(unsafe.Pointer(&out[i])), reply[i*eventSize:(i+1)*eventSize])
	}
	return out, nil
}

// The wire structs are fixed 128-byte little-endian extern layouts; the Go
// struct definitions in types.go are laid out field-for-field identically
// (all fields are fixed-size scalars/arrays, so Go inserts no padding on
// 64-bit targets — guarded by the size check in sample/main.go).
func structBytes(p unsafe.Pointer) []byte {
	return unsafe.Slice((*byte)(p), eventSize)
}

func structSlice(p unsafe.Pointer) []byte {
	return unsafe.Slice((*byte)(p), eventSize)
}

// -- async packet API (the reference's packet/completion model) ----------
//
// AsyncClient owns a pool of sessions; Submit* return a channel that
// yields the result when its request completes. Go's equivalent of the C
// tb_client_async session pool (native/tb_client.h): goroutines multiplex
// a shared work queue over N blocking sessions — the idiomatic Go shape
// for N-in-flight, no cgo callback trampoline needed.

// AsyncResult carries one completed packet.
type AsyncResult struct {
	Reply []byte
	Err   error
}

type asyncWork struct {
	op       uint8
	body     []byte
	replyCap int
	done     chan AsyncResult
}

// AsyncClient is a pool of sessions driving a shared packet queue.
type AsyncClient struct {
	sessions []*Client
	work     chan asyncWork
	stop     chan struct{}
	wg       sync.WaitGroup
	// submitters in flight: Close drains the queue only after every
	// concurrent submit has finished its send (a select may pick the
	// buffered send even with stop already closed — without this barrier
	// that work could land after the drain and never complete)
	subWG sync.WaitGroup
}

// NewAsyncClient registers `sessions` sessions and starts their workers.
func NewAsyncClient(addresses string, cluster uint32, sessions int) (*AsyncClient, error) {
	if sessions < 1 {
		sessions = 1
	}
	a := &AsyncClient{
		work: make(chan asyncWork, sessions*4),
		stop: make(chan struct{}),
	}
	for i := 0; i < sessions; i++ {
		c, err := NewClient(addresses, cluster)
		if err != nil {
			a.Close()
			return nil, err
		}
		a.sessions = append(a.sessions, c)
		a.wg.Add(1)
		go func(c *Client) {
			defer a.wg.Done()
			for {
				select {
				case w := <-a.work:
					reply, err := c.request(w.op, w.body, w.replyCap)
					w.done <- AsyncResult{Reply: reply, Err: err}
				case <-a.stop:
					return
				}
			}
		}(c)
	}
	return a, nil
}

func (a *AsyncClient) submit(op uint8, body []byte, replyCap int) chan AsyncResult {
	done := make(chan AsyncResult, 1)
	a.subWG.Add(1)
	defer a.subWG.Done()
	select {
	case a.work <- asyncWork{op: op, body: body, replyCap: replyCap, done: done}:
	case <-a.stop:
		done <- AsyncResult{Err: errors.New("async client closed")}
	}
	return done
}

// SubmitCreateTransfers enqueues a batch; receive from the returned channel
// for its sparse results.
func (a *AsyncClient) SubmitCreateTransfers(transfers []Transfer) chan AsyncResult {
	body := make([]byte, 0, len(transfers)*eventSize)
	for i := range transfers {
		body = append(body, structBytes(unsafe.Pointer(&transfers[i]))...)
	}
	return a.submit(opCreateTransfers, body, len(transfers)*resultSize)
}

// SubmitCreateAccounts enqueues a batch of account creates.
func (a *AsyncClient) SubmitCreateAccounts(accounts []Account) chan AsyncResult {
	body := make([]byte, 0, len(accounts)*eventSize)
	for i := range accounts {
		body = append(body, structBytes(unsafe.Pointer(&accounts[i]))...)
	}
	return a.submit(opCreateAccounts, body, len(accounts)*resultSize)
}

// Close stops the workers, waits for in-flight requests to complete, fails
// any queued-but-unstarted work, then deinits every session (never while a
// worker is still inside the native library).
func (a *AsyncClient) Close() {
	close(a.stop)
	a.wg.Wait()
	a.subWG.Wait() // no send can land after this: the drain below is final
	for {
		select {
		case w := <-a.work:
			w.done <- AsyncResult{Err: errors.New("async client closed")}
			continue
		default:
		}
		break
	}
	for _, c := range a.sessions {
		c.Close()
	}
	a.sessions = nil
}
